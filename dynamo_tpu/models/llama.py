"""Llama-family decoder (llama 2/3, mistral, qwen2/qwen3) — pure-functional jax.

The reference framework never implements a model; it shells out to vLLM/SGLang
on CUDA (SURVEY §2.5). Here the model loop is native and TPU-first, with two
interchangeable forwards over the same weights:

- ``forward`` — ONE ``lax.scan`` over stacked per-layer params: a single
  compiled layer body, fast compiles, XLA while-loop buffer aliasing keeps the
  stacked paged KV cache (scan carry) updated in place. This is the portable
  path (CPU tests, prefill-heavy work).
- ``forward_unrolled`` — python loop over layers with a *list* of per-layer
  KV buffers. Exists for the Pallas decode kernel, which wants a concrete
  per-layer HBM ref (a traced layer-slice of a stacked cache forces XLA to
  defensively copy the whole cache around the opaque custom call —
  measured 10x worse than the list, aliasing declarations included).
  Longer compile, fastest decode; the serving engine uses it on TPU.

Both share the exact same math (``_layer_step``); equivalence is tested.

Only the last real token's logits are computed ([B, V]); full [B, S, V]
logit materialization would waste HBM on long prefill chunks.

Weight layout matches HF checkpoints after transpose (torch Linear stores
[out, in]; we store [in, out] so the forward is ``x @ w``).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.ops.attention import (
    paged_attention,
    paged_attention_layer,
    write_kv,
    write_kv_layer,
)
from dynamo_tpu.ops.rope import apply_rope
from dynamo_tpu.ops import quant

Params = Dict[str, Any]


def _rms_norm(x: jnp.ndarray, w: jnp.ndarray, eps: float) -> jnp.ndarray:
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    return (xf * jax.lax.rsqrt(var + eps)).astype(x.dtype) * w


def _head_rms_norm(x: jnp.ndarray, w: jnp.ndarray, eps: float) -> jnp.ndarray:
    """qwen3-style per-head norm: x is [B, S, H, Dh], w is [Dh]."""
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    return (xf * jax.lax.rsqrt(var + eps)).astype(x.dtype) * w


def make_pages(cfg: ModelConfig, num_pages: int, page_size: int,
               dtype=None) -> jnp.ndarray:
    """Stacked paged KV cache: [L, N, 2, Hkv, page_size, Dh] (scan path).

    Page-major: one page is a contiguous slab carrying K AND V for all kv
    heads, so page-granular DMAs (Pallas decode kernel, disagg block
    transfer) are single descriptors (see ``ops/attention.py``).

    Page 0 is reserved as the garbage page for padded writes — allocators must
    hand out pages starting at index 1.
    """
    dtype = dtype or jnp.dtype(cfg.dtype)
    return jnp.zeros((cfg.num_layers, num_pages, 2, cfg.num_kv_heads,
                      page_size, cfg.head_dim), dtype=dtype)


def make_pages_list(cfg: ModelConfig, num_pages: int, page_size: int,
                    dtype=None) -> List[jnp.ndarray]:
    """Per-layer KV buffers [N, 2, Hkv, page_size, Dh] (unrolled path)."""
    dtype = dtype or jnp.dtype(cfg.dtype)
    return [jnp.zeros((num_pages, 2, cfg.num_kv_heads, page_size,
                       cfg.head_dim), dtype=dtype)
            for _ in range(cfg.num_layers)]


def init_params(cfg: ModelConfig, rng: jax.Array, scale: float = 0.02) -> Params:
    """Random-normal init (for tests/benchmarks; real serving loads HF weights)."""
    dtype = jnp.dtype(cfg.dtype)
    keys = iter(jax.random.split(rng, 16))

    def norm(shape):
        return jnp.ones(shape, dtype=dtype)

    def randn(key, shape):
        return (jax.random.normal(key, shape, dtype=jnp.float32) * scale).astype(dtype)

    L, H, I = cfg.num_layers, cfg.hidden_size, cfg.intermediate_size
    layers: Dict[str, jnp.ndarray] = {
        "attn_norm": norm((L, H)),
        "wq": randn(next(keys), (L, H, cfg.q_size)),
        "wk": randn(next(keys), (L, H, cfg.kv_size)),
        "wv": randn(next(keys), (L, H, cfg.kv_size)),
        "wo": randn(next(keys), (L, cfg.q_size, H)),
        "mlp_norm": norm((L, H)),
        "w_gate": randn(next(keys), (L, H, I)),
        "w_up": randn(next(keys), (L, H, I)),
        "w_down": randn(next(keys), (L, I, H)),
    }
    if cfg.attention_bias:
        layers["bq"] = jnp.zeros((L, cfg.q_size), dtype=dtype)
        layers["bk"] = jnp.zeros((L, cfg.kv_size), dtype=dtype)
        layers["bv"] = jnp.zeros((L, cfg.kv_size), dtype=dtype)
    if cfg.qk_norm:
        layers["q_norm"] = norm((L, cfg.head_dim))
        layers["k_norm"] = norm((L, cfg.head_dim))
    params: Params = {
        "embed": randn(next(keys), (cfg.vocab_size, H)),
        "layers": layers,
        "final_norm": norm((H,)),
    }
    if not cfg.tie_word_embeddings:
        params["lm_head"] = randn(next(keys), (H, cfg.vocab_size))
    return params


def _project_qkv(cfg: ModelConfig, lp: Dict[str, jnp.ndarray],
                 h: jnp.ndarray, positions: jnp.ndarray
                 ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Shared per-layer pre-attention math: norm, qkv, qk-norm, rope."""
    B, S, _ = h.shape
    eps = cfg.rms_norm_eps
    x = _rms_norm(h, lp["attn_norm"], eps)
    q = quant.mm(lp, "wq", x)
    k = quant.mm(lp, "wk", x)
    v = quant.mm(lp, "wv", x)
    if cfg.attention_bias:
        q = q + lp["bq"]
        k = k + lp["bk"]
        v = v + lp["bv"]
    q = q.reshape(B, S, cfg.num_heads, cfg.head_dim)
    k = k.reshape(B, S, cfg.num_kv_heads, cfg.head_dim)
    v = v.reshape(B, S, cfg.num_kv_heads, cfg.head_dim)
    if cfg.qk_norm:
        q = _head_rms_norm(q, lp["q_norm"], eps)
        k = _head_rms_norm(k, lp["k_norm"], eps)
    q = apply_rope(q, positions, cfg.rope_theta)
    k = apply_rope(k, positions, cfg.rope_theta)
    return q, k, v


def _finish_attn(cfg: ModelConfig, lp: Dict[str, jnp.ndarray],
                 h: jnp.ndarray, attn: jnp.ndarray) -> jnp.ndarray:
    """Out-projection residual (shared with the MoE decoder)."""
    B, S, _ = h.shape
    return h + quant.mm(lp, "wo", attn.reshape(B, S, cfg.q_size))


def _finish_layer(cfg: ModelConfig, lp: Dict[str, jnp.ndarray],
                  h: jnp.ndarray, attn: jnp.ndarray) -> jnp.ndarray:
    """Shared post-attention math: out-proj residual + gated MLP residual."""
    h = _finish_attn(cfg, lp, h, attn)
    x = _rms_norm(h, lp["mlp_norm"], cfg.rms_norm_eps)
    act = jax.nn.silu(quant.mm(lp, "w_gate", x)) * quant.mm(lp, "w_up", x)
    return h + quant.mm(lp, "w_down", act)


def _logits(cfg: ModelConfig, params: Params, h: jnp.ndarray,
            new_lens: jnp.ndarray, window: int = 1) -> jnp.ndarray:
    """Logits at each row's last ``window`` real new positions.

    window == 1 (every normal step) returns [B, V]; window = W > 1 (the
    speculative-verify step, which samples at all K+1 chunk slots) returns
    [B, W, V]. Only W rows of hidden state hit the lm_head either way —
    full [B, S, V] materialization stays off the table.
    """
    h = _rms_norm(h, params["final_norm"], cfg.rms_norm_eps)
    if window == 1:
        last = jnp.maximum(new_lens - 1, 0)                # [B]
        h_sel = jnp.take_along_axis(
            h, last[:, None, None].astype(jnp.int32), axis=1)[:, 0]  # [B, H]
    else:
        offs = jnp.arange(window, dtype=jnp.int32)[None, :]          # [1, W]
        idx = jnp.maximum(new_lens[:, None] - window + offs, 0)      # [B, W]
        h_sel = jnp.take_along_axis(h, idx[..., None], axis=1)       # [B,W,H]
    lm8 = params.get("lm_head_q")
    if lm8 is not None:
        return quant.qdot(h_sel, lm8, params["lm_head_scale"],
                          out_dtype=jnp.float32)
    lm_head = params.get("lm_head")
    if lm_head is None:
        lm_head = params["embed"].T
    # operands stay in the model dtype with f32 ACCUMULATION: casting
    # lm_head to f32 would double its HBM stream (the largest single
    # tensor of a decode step) and push the matmul off the bf16 MXU path
    return jnp.dot(h_sel, lm_head, preferred_element_type=jnp.float32)


def forward(params: Params, cfg: ModelConfig, tokens: jnp.ndarray,
            positions: jnp.ndarray, pages: jnp.ndarray,
            page_table: jnp.ndarray, total_lens: jnp.ndarray,
            new_lens: jnp.ndarray,
            attn_impl: Optional[Callable] = None,
            logits_window: int = 1
            ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Scan-over-layers forward against the stacked paged cache.

    tokens:     [B, S] new token ids (padded; pads masked via new_lens)
    positions:  [B, S] absolute positions of the new tokens
    pages:      stacked paged KV cache (see make_pages); returned updated
    page_table: [B, P] physical page ids per sequence
    total_lens: [B] context length including the new tokens
    new_lens:   [B] real new tokens per sequence (<= S)
    attn_impl:  optional stacked-cache attention override with
                ``paged_attention``'s signature — the engine passes the
                Pallas decode kernel (``paged_decode_attention_stacked``)
                for S == 1 steps on TPU; the traced scan index selects the
                layer inside the kernel's DMA, so decode keeps the
                single-compiled-layer-body scan.

    Returns (logits [B, vocab] at each sequence's last real new token, pages).
    """
    sm_scale = cfg.head_dim ** -0.5
    attn_impl = attn_impl or paged_attention
    h = params["embed"][tokens]  # [B, S, H]

    def body(carry, xs):
        h, pages = carry
        lp, lidx = xs
        q, k, v = _project_qkv(cfg, lp, h, positions)
        pages = write_kv(pages, lidx, k, v, page_table, positions, new_lens)
        attn = attn_impl(q, pages, lidx, page_table, positions,
                         total_lens, sm_scale)
        h = _finish_layer(cfg, lp, h, attn)
        return (h, pages), None

    (h, pages), _ = jax.lax.scan(
        body, (h, pages),
        (params["layers"], jnp.arange(cfg.num_layers)))
    return _logits(cfg, params, h, new_lens, window=logits_window), pages


def _dense_hidden(params: Params, cfg: ModelConfig, tokens: jnp.ndarray,
                  mask: jnp.ndarray) -> jnp.ndarray:
    """Causal dense (non-paged) transformer forward shared by the
    one-shot surfaces — ``encode`` (embeddings pooling) and ``score``
    (prompt logprobs). Materializes [B, H, S, S] attention scores per
    layer (under the scan), so callers must bound S. Returns the
    final-norm hidden states [B, S, H]."""
    B, S = tokens.shape
    sm_scale = cfg.head_dim ** -0.5
    positions = jnp.tile(jnp.arange(S, dtype=jnp.int32)[None], (B, 1))
    h = params["embed"][tokens]

    causal = jnp.tril(jnp.ones((S, S), bool))
    attn_mask = causal[None, None] & mask[:, None, None, :]  # [B,1,S,S]

    def body(h, lp):
        q, k, v = _project_qkv(cfg, lp, h, positions)
        if cfg.num_kv_heads != cfg.num_heads:
            rep = cfg.num_heads // cfg.num_kv_heads
            k = jnp.repeat(k, rep, axis=2)
            v = jnp.repeat(v, rep, axis=2)
        scores = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                            k.astype(jnp.float32)) * sm_scale
        scores = jnp.where(attn_mask, scores, -1e30)
        probs = jax.nn.softmax(scores, axis=-1)
        attn = jnp.einsum("bhqk,bkhd->bqhd", probs, v.astype(jnp.float32))
        attn = attn.astype(h.dtype)
        h = _finish_layer(cfg, lp, h, attn)
        return h, None

    h, _ = jax.lax.scan(body, h, params["layers"])
    return _rms_norm(h, params["final_norm"], cfg.rms_norm_eps)


def encode(params: Params, cfg: ModelConfig, tokens: jnp.ndarray,
           mask: jnp.ndarray) -> jnp.ndarray:
    """Dense (non-paged) forward for embeddings: mean-pooled final hidden
    state over real tokens. tokens/mask: [B, S]; returns [B, H] float32.

    Serves the /v1/embeddings surface (reference: ``http/service/openai.rs``
    embeddings route; the reference delegates the model to an engine)."""
    h = _dense_hidden(params, cfg, tokens, mask)
    m = mask.astype(jnp.float32)[..., None]
    pooled = jnp.sum(h.astype(jnp.float32) * m, axis=1) / jnp.maximum(
        jnp.sum(m, axis=1), 1.0)
    return pooled


def score(params: Params, cfg: ModelConfig, tokens: jnp.ndarray,
          mask: jnp.ndarray, chunk: int = 256, top_n: int = 1
          ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Prompt scoring for the OpenAI ``echo`` + logprobs surface (the
    lm-eval loglikelihood workflow): log P(token[j] | tokens[:j]) for every
    position, plus the ``top_n`` highest alternatives at each position.

    Dense causal forward (no KV cache — shares :func:`_dense_hidden` with
    ``encode``; the caller bounds S, see JaxEngine._score_batch), with the
    LM head applied per S-chunk under ``lax.scan`` so the full [B, S, V]
    logits tensor never materializes.

    tokens/mask: [B, S] (S padded to a multiple of ``chunk``)
    returns (target_lps [B, S] f32 — position 0 is 0 (no context),
             top_ids [B, S, top_n] i32, top_lps [B, S, top_n] f32) —
    tops at position j are the model's best alternatives for position j
    given tokens[:j].
    """
    B, S = tokens.shape
    h = _dense_hidden(params, cfg, tokens, mask)
    lm8 = params.get("lm_head_q")
    lm_head = params.get("lm_head")
    if lm_head is None and lm8 is None:
        lm_head = params["embed"].T

    # chunked LM head: position j-1's logits score token j
    nc = S // chunk
    h_c = h.reshape(B, nc, chunk, -1).swapaxes(0, 1)       # [nc, B, c, H]
    # targets for chunk c, slot k = tokens[:, c*chunk + k + 1]
    tgt = jnp.concatenate([tokens[:, 1:], tokens[:, :1]], axis=1)
    t_c = tgt.reshape(B, nc, chunk).swapaxes(0, 1)         # [nc, B, c]

    def head_chunk(_, xs):
        hc, tc = xs
        if lm8 is not None:       # int8-quantized serving: same head
            logits = quant.qdot(hc, lm8, params["lm_head_scale"],
                                out_dtype=jnp.float32)
        else:
            logits = jnp.dot(hc, lm_head,
                             preferred_element_type=jnp.float32)  # [B,c,V]
        lsm = jax.nn.log_softmax(logits, axis=-1)
        t_lp = jnp.take_along_axis(lsm, tc[..., None], axis=-1)[..., 0]
        top_lp, top_id = jax.lax.top_k(lsm, top_n)    # [B, c, top_n]
        return None, (t_lp, top_id.astype(jnp.int32), top_lp)

    _, (t_lp, top_id, top_lp) = jax.lax.scan(head_chunk, None, (h_c, t_c))
    # [nc, B, c, ...] -> [B, S, ...]; shift: position j-1 scored token j
    def unchunk(a):
        return a.swapaxes(0, 1).reshape((B, S) + a.shape[3:])
    t_lp, top_id, top_lp = unchunk(t_lp), unchunk(top_id), unchunk(top_lp)
    z = jnp.zeros((B, 1), jnp.float32)
    target_lps = jnp.concatenate([z, t_lp[:, :-1]], axis=1)
    top_ids = jnp.concatenate(
        [jnp.zeros((B, 1, top_n), jnp.int32), top_id[:, :-1]], axis=1)
    top_lps = jnp.concatenate(
        [jnp.zeros((B, 1, top_n), jnp.float32), top_lp[:, :-1]], axis=1)
    return target_lps, top_ids, top_lps


def forward_unrolled(params: Params, cfg: ModelConfig, tokens: jnp.ndarray,
                     positions: jnp.ndarray, pages_list: List[jnp.ndarray],
                     page_table: jnp.ndarray, total_lens: jnp.ndarray,
                     new_lens: jnp.ndarray,
                     attn_impl: Optional[Callable] = None,
                     logits_window: int = 1
                     ) -> Tuple[jnp.ndarray, List[jnp.ndarray]]:
    """Unrolled forward over per-layer KV buffers (Pallas-kernel path).

    ``attn_impl(q, kv_layer, page_table, positions, total_lens, sm_scale)``
    defaults to the XLA gather path; the engine passes the Pallas decode
    kernel for S == 1 steps on TPU.
    """
    sm_scale = cfg.head_dim ** -0.5
    attn_impl = attn_impl or paged_attention_layer
    h = params["embed"][tokens]
    out_pages: List[jnp.ndarray] = []
    for l in range(cfg.num_layers):
        lp = {k: v[l] for k, v in params["layers"].items()}
        q, k, v = _project_qkv(cfg, lp, h, positions)
        kv = write_kv_layer(pages_list[l], k, v, page_table, positions,
                            new_lens)
        attn = attn_impl(q, kv, page_table, positions, total_lens, sm_scale)
        h = _finish_layer(cfg, lp, h, attn)
        out_pages.append(kv)
    return _logits(cfg, params, h, new_lens, window=logits_window), out_pages


__all__ = ["init_params", "forward", "forward_unrolled", "encode", "score",
           "make_pages", "make_pages_list"]
