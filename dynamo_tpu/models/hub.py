"""Model intake: resolve a local path OR download from the HF hub.

Capability parity: reference ``lib/llm/src/hub.rs`` (``from_hf`` — snapshot
download of config/tokenizer/weights into the HF cache, honoring offline
mode and revisions). A worker can be launched with
``--model-path meta-llama/Llama-3.2-1B`` and the checkpoint resolves
through the standard HF cache (``HF_HOME``/``HF_HUB_CACHE``), or instantly
when already cached / running offline (``HF_HUB_OFFLINE=1``).

Only inference-relevant files are pulled: config, tokenizer, safetensors
(never .bin/.pth duplicates or training states).
"""

from __future__ import annotations

import logging
import os
from typing import Optional

logger = logging.getLogger(__name__)

# what an inference worker needs — mirrors hub.rs's ignore-list approach
# from the allow side
ALLOW_PATTERNS = [
    "*.json", "*.safetensors", "tokenizer.model", "*.gguf",
]


def is_local(name_or_path: str) -> bool:
    return (os.path.isdir(name_or_path)
            or (os.path.isfile(name_or_path)
                and name_or_path.endswith(".gguf")))


def resolve_model_path(name_or_path: str, revision: Optional[str] = None,
                       cache_dir: Optional[str] = None) -> str:
    """Return a local directory (or .gguf file) for a model reference.

    Local paths pass through untouched; anything else is treated as an HF
    repo id and snapshot-downloaded (cache-first, so a warm cache or
    ``HF_HUB_OFFLINE=1`` never touches the network)."""
    if is_local(name_or_path):
        return name_or_path
    if os.path.sep in name_or_path and not _looks_like_repo_id(name_or_path):
        raise FileNotFoundError(
            f"model path {name_or_path!r} does not exist locally and is "
            f"not an HF repo id")
    try:
        from huggingface_hub import snapshot_download
    except ImportError as e:  # pragma: no cover
        raise RuntimeError(
            f"{name_or_path!r} is not a local path and huggingface_hub is "
            f"unavailable to download it") from e
    logger.info("resolving %s via the HF hub (cache-first)", name_or_path)
    return snapshot_download(
        repo_id=name_or_path, revision=revision, cache_dir=cache_dir,
        allow_patterns=ALLOW_PATTERNS)


def _looks_like_repo_id(s: str) -> bool:
    """org/name with exactly one slash and no leading dot/slash."""
    parts = s.split("/")
    return (len(parts) == 2 and all(parts)
            and not s.startswith((".", "/", "~")))


__all__ = ["resolve_model_path", "is_local", "ALLOW_PATTERNS"]
