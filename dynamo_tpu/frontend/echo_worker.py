"""Echo test worker: registers a model served by the EchoEngine.

Parity in role with the reference's echo engines (``lib/llm/src/engines.rs``)
exposed as a worker process — used for frontend e2e tests without hardware.
"""

from __future__ import annotations

import argparse
import asyncio

from dynamo_tpu.engine.base import EchoEngine
from dynamo_tpu.llm.register import register_llm, serve_engine
from dynamo_tpu.model_card import ModelDeploymentCard
from dynamo_tpu.runtime.runtime import DEFAULT_COORDINATOR, DistributedRuntime
from dynamo_tpu.utils.logging import configure_logging
from dynamo_tpu.utils.testing import make_test_card


async def amain(args: argparse.Namespace) -> None:
    drt = await DistributedRuntime.create(coordinator=args.coordinator)
    if args.model_path:
        card = ModelDeploymentCard.from_local_path(args.model_path,
                                                   name=args.model_name)
    else:
        card = make_test_card(name=args.model_name or "echo-model")
    endpoint = (drt.namespace(args.namespace).component(args.component)
                .endpoint("generate"))
    engine = EchoEngine(delay_s=args.token_delay)
    await serve_engine(endpoint, engine)
    await register_llm(drt, endpoint, card)
    print(f"echo worker serving model {card.name}", flush=True)
    try:
        await drt.runtime.wait_shutdown()
    finally:
        await drt.close()


def main() -> None:
    parser = argparse.ArgumentParser(description="dynamo_tpu echo worker")
    parser.add_argument("--coordinator", default=DEFAULT_COORDINATOR)
    parser.add_argument("--namespace", default="dynamo")
    parser.add_argument("--component", default="echo")
    parser.add_argument("--model-name", default=None)
    parser.add_argument("--model-path", default=None,
                        help="HF-style local model dir (tokenizer/config)")
    parser.add_argument("--token-delay", type=float, default=0.0)
    args = parser.parse_args()
    configure_logging()
    try:
        asyncio.run(amain(args))
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
