"""CLI entrypoints (parity: reference ``components/`` deployables).

- ``python -m dynamo_tpu.frontend.coordinator`` — control-plane service
- ``python -m dynamo_tpu.frontend.main`` — OpenAI frontend (HTTP + discovery)
- ``python -m dynamo_tpu.frontend.echo_worker`` — echo test worker
- ``python -m dynamo_tpu.frontend.mocker_worker`` — mock vLLM-style worker
- ``python -m dynamo_tpu.frontend.tpu_worker`` — the jax/TPU model worker
"""
