"""Standalone coordinator process: ``python -m dynamo_tpu.frontend.coordinator``."""

from __future__ import annotations

import argparse
import asyncio
import logging

from dynamo_tpu.runtime.coordinator import Coordinator
from dynamo_tpu.utils.logging import configure_logging


async def amain(args: argparse.Namespace) -> None:
    coord = await Coordinator(host=args.host, port=args.port).start()
    print(f"coordinator listening on {coord.address}", flush=True)
    try:
        await asyncio.Event().wait()
    finally:
        await coord.stop()


def main() -> None:
    parser = argparse.ArgumentParser(description="dynamo_tpu coordinator")
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--port", type=int, default=6650)
    args = parser.parse_args()
    configure_logging()
    try:
        asyncio.run(amain(args))
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
