"""OpenAI frontend: HTTP service + model discovery + router in one process.

Parity: reference ``components/frontend/src/dynamo/frontend/main.py`` —
flags ``--router-mode {round-robin,random,kv}``, ``--kv-overlap-score-weight``,
``--router-temperature``, ``--http-port``; plus ``--standalone`` to embed a
coordinator (for single-node / dev runs).
"""

from __future__ import annotations

import argparse
import asyncio
import logging

from dynamo_tpu.http.service import HttpService
from dynamo_tpu.llm.model_manager import ModelManager, ModelWatcher
from dynamo_tpu.runtime.push_router import RouterMode
from dynamo_tpu.runtime.resilience import RouterPolicyConfig
from dynamo_tpu.runtime.runtime import DEFAULT_COORDINATOR, DistributedRuntime
from dynamo_tpu.utils.config import RuntimeConfig
from dynamo_tpu.utils.logging import configure_logging

logger = logging.getLogger(__name__)


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(description="dynamo_tpu OpenAI frontend")
    parser.add_argument("--coordinator", default=DEFAULT_COORDINATOR)
    parser.add_argument("--standalone", action="store_true",
                        help="embed a coordinator in this process")
    parser.add_argument("--http-host", default="0.0.0.0")
    parser.add_argument("--http-port", type=int, default=8080)
    parser.add_argument("--router-mode", default="round-robin",
                        choices=["round-robin", "random", "kv", "cost"])
    parser.add_argument("--kv-overlap-score-weight", type=float, default=1.0)
    parser.add_argument("--router-temperature", type=float, default=0.0)
    parser.add_argument("--no-kv-events", action="store_true",
                        help="KV router predicts cache contents instead of "
                             "subscribing to worker events")
    # fleet-wide KV reuse (docs/deployment.md "Fleet-wide KV reuse"):
    # consult the coordinator-backed global prefix index so prefix-heavy
    # requests route to holders anywhere in the fleet, priced against the
    # kv_transfer plane bandwidth EWMAs
    parser.add_argument("--kv-global-index", action="store_true",
                        help="kv mode: merge the coordinator-backed global "
                             "prefix index into routing, so remote holders "
                             "compete with local cache hits")
    parser.add_argument("--kv-block-bytes", type=int, default=0,
                        help="estimated KV bytes per block for pricing "
                             "remote-prefix transfers (0 disables the "
                             "net-cost credit; set to the workers' "
                             "per-block KV footprint)")
    parser.add_argument("--kv-net-cost-weight", type=float, default=25.0,
                        help="weight of the estimated transfer-seconds term "
                             "when pricing a remote prefix hit against "
                             "local recompute")
    # request-lifecycle robustness knobs; defaults layer through
    # RuntimeConfig (dataclass defaults -> TOML -> DYN_RUNTIME_* env)
    try:
        cfg = RuntimeConfig.load()
    except Exception:
        # a malformed config file/env must not take out --help (or hide
        # the argparse usage behind a traceback); flag values still win
        logger.warning("bad runtime config; using built-in defaults for "
                       "CLI flag defaults", exc_info=True)
        cfg = RuntimeConfig()
    parser.add_argument("--request-timeout-s", type=float,
                        default=cfg.request_timeout_s,
                        help="default end-to-end request deadline in seconds "
                             "(0 disables; per-request nvext.timeout_s or "
                             "X-Request-Timeout override)")
    parser.add_argument("--max-inflight", type=int,
                        default=cfg.http_max_inflight,
                        help="shed (503 + Retry-After) past this many "
                             "concurrent requests (0 = unlimited)")
    parser.add_argument("--max-model-inflight", type=int,
                        default=cfg.http_max_model_inflight,
                        help="per-model concurrent-request high-water mark "
                             "(0 = unlimited)")
    parser.add_argument("--shed-retry-after-s", type=float,
                        default=cfg.http_shed_retry_after_s,
                        help="Retry-After hint on shed responses")
    # SLO targets for goodput accounting (docs/observability.md "Step
    # timeline & goodput"): dynamo_frontend_slo_total judgments per
    # request plus dynamo_frontend_goodput_tokens_total for tokens from
    # requests inside every enabled target
    parser.add_argument("--slo-ttft-s", type=float, default=0.0,
                        help="TTFT SLO target in seconds (0 disables)")
    parser.add_argument("--slo-itl-s", type=float, default=0.0,
                        help="inter-token-latency SLO target in seconds, "
                             "judged against each request's worst "
                             "per-token gap (0 disables)")
    # failure-aware routing knobs (cost + kv modes; see docs/deployment.md
    # "Failure-aware routing")
    parser.add_argument("--breaker-failures", type=int,
                        default=cfg.router_breaker_failures,
                        help="consecutive failures that open an instance's "
                             "circuit breaker")
    parser.add_argument("--breaker-cooldown-s", type=float,
                        default=cfg.router_breaker_cooldown_s,
                        help="breaker open -> half-open probe dwell "
                             "(doubles per re-open)")
    parser.add_argument("--breaker-slow-ttft-s", type=float,
                        default=cfg.router_breaker_slow_ttft_s,
                        help="TTFT at or above this counts as a breaker "
                             "failure (0 disables slow-call accounting)")
    parser.add_argument("--retry-budget", type=float,
                        default=cfg.router_retry_budget,
                        help="retry-budget tokens earned per request (~max "
                             "fraction of requests that may retry/hedge)")
    parser.add_argument("--hedge", action="store_true",
                        default=cfg.router_hedge,
                        help="hedge slow first tokens on the next-best "
                             "instance (first winner cancels the loser)")
    parser.add_argument("--hedge-delay-s", type=float,
                        default=cfg.router_hedge_delay_s,
                        help="fixed hedge delay (0 = observed p95 TTFT)")
    parser.add_argument("--router-stats-interval-s", type=float,
                        default=cfg.router_stats_interval_s,
                        help="worker __stats__ scrape period for the cost "
                             "score")
    return parser


async def amain(args: argparse.Namespace) -> None:
    drt = await DistributedRuntime.create(
        coordinator=args.coordinator, standalone=args.standalone)
    manager = ModelManager()
    policy_config = RouterPolicyConfig(
        breaker_failures=args.breaker_failures,
        breaker_cooldown_s=args.breaker_cooldown_s,
        breaker_slow_ttft_s=args.breaker_slow_ttft_s,
        retry_budget_ratio=args.retry_budget,
        hedge=args.hedge,
        hedge_delay_s=args.hedge_delay_s,
        stats_interval_s=args.router_stats_interval_s,
        net_weight=args.kv_net_cost_weight)
    watcher = ModelWatcher(
        drt, manager,
        router_mode=RouterMode(args.router_mode),
        kv_router_config={
            "overlap_score_weight": args.kv_overlap_score_weight,
            "temperature": args.router_temperature,
            "use_kv_events": not args.no_kv_events,
            "use_global_index": args.kv_global_index,
            "kv_block_bytes": args.kv_block_bytes,
            "net_weight": args.kv_net_cost_weight,
        },
        policy_config=policy_config)
    await watcher.start()
    service = HttpService(
        manager, host=args.http_host, port=args.http_port,
        request_timeout_s=args.request_timeout_s,
        max_inflight=args.max_inflight,
        max_model_inflight=args.max_model_inflight,
        shed_retry_after_s=args.shed_retry_after_s,
        slo_ttft_s=args.slo_ttft_s, slo_itl_s=args.slo_itl_s)
    # control-plane health rides the same /metrics page as request metrics
    # (dynamo_coord_connected, dynamo_coord_reconnects_total, ...) and
    # gates GET /healthz/ready (503 while disconnected, so load balancers
    # route around a control-plane outage)
    service.attach_coord(drt.coord)
    await service.start()
    if args.standalone:
        print(f"coordinator listening on {drt._embedded.address}", flush=True)
    print(f"frontend listening on {service.host}:{service.port}", flush=True)
    try:
        await drt.runtime.wait_shutdown()
    except asyncio.CancelledError:
        pass
    finally:
        await service.stop()
        await watcher.stop()
        await drt.close()


def main() -> None:
    args = build_parser().parse_args()
    configure_logging()
    try:
        asyncio.run(amain(args))
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
