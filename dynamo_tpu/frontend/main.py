"""OpenAI frontend: HTTP service + model discovery + router in one process.

Parity: reference ``components/frontend/src/dynamo/frontend/main.py`` —
flags ``--router-mode {round-robin,random,kv}``, ``--kv-overlap-score-weight``,
``--router-temperature``, ``--http-port``; plus ``--standalone`` to embed a
coordinator (for single-node / dev runs).
"""

from __future__ import annotations

import argparse
import asyncio
import logging

from dynamo_tpu.http.service import HttpService
from dynamo_tpu.llm.model_manager import ModelManager, ModelWatcher
from dynamo_tpu.runtime.push_router import RouterMode
from dynamo_tpu.runtime.runtime import DEFAULT_COORDINATOR, DistributedRuntime
from dynamo_tpu.utils.logging import configure_logging

logger = logging.getLogger(__name__)


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(description="dynamo_tpu OpenAI frontend")
    parser.add_argument("--coordinator", default=DEFAULT_COORDINATOR)
    parser.add_argument("--standalone", action="store_true",
                        help="embed a coordinator in this process")
    parser.add_argument("--http-host", default="0.0.0.0")
    parser.add_argument("--http-port", type=int, default=8080)
    parser.add_argument("--router-mode", default="round-robin",
                        choices=["round-robin", "random", "kv"])
    parser.add_argument("--kv-overlap-score-weight", type=float, default=1.0)
    parser.add_argument("--router-temperature", type=float, default=0.0)
    parser.add_argument("--no-kv-events", action="store_true",
                        help="KV router predicts cache contents instead of "
                             "subscribing to worker events")
    return parser


async def amain(args: argparse.Namespace) -> None:
    drt = await DistributedRuntime.create(
        coordinator=args.coordinator, standalone=args.standalone)
    manager = ModelManager()
    watcher = ModelWatcher(
        drt, manager,
        router_mode=RouterMode(args.router_mode),
        kv_router_config={
            "overlap_score_weight": args.kv_overlap_score_weight,
            "temperature": args.router_temperature,
            "use_kv_events": not args.no_kv_events,
        })
    await watcher.start()
    service = await HttpService(manager, host=args.http_host,
                                port=args.http_port).start()
    if args.standalone:
        print(f"coordinator listening on {drt._embedded.address}", flush=True)
    print(f"frontend listening on {service.host}:{service.port}", flush=True)
    try:
        await drt.runtime.wait_shutdown()
    except asyncio.CancelledError:
        pass
    finally:
        await service.stop()
        await watcher.stop()
        await drt.close()


def main() -> None:
    args = build_parser().parse_args()
    configure_logging()
    try:
        asyncio.run(amain(args))
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
