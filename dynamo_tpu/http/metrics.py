"""Per-request Prometheus metrics for the HTTP frontend.

Parity: reference ``lib/llm/src/http/service/metrics.rs`` (~500 LoC): request
counters by model/endpoint/status, TTFT and inter-token-latency histograms,
inflight gauge, request duration.
"""

from __future__ import annotations

import time
from typing import Optional

from prometheus_client import (
    CollectorRegistry,
    Counter,
    Gauge,
    Histogram,
    generate_latest,
)

_TTFT_BUCKETS = (0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
                 5.0, 10.0, 30.0)
_ITL_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                0.5, 1.0)
_DUR_BUCKETS = (0.01, 0.05, 0.1, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0,
                300.0)


class FrontendMetrics:
    def __init__(self, registry: Optional[CollectorRegistry] = None):
        self.registry = registry or CollectorRegistry()
        ns = "dynamo_frontend"
        self.requests_total = Counter(
            f"{ns}_requests_total", "HTTP requests",
            ["model", "endpoint", "status"], registry=self.registry)
        self.inflight = Gauge(
            f"{ns}_inflight_requests", "Concurrent requests",
            ["model"], registry=self.registry)
        self.ttft = Histogram(
            f"{ns}_time_to_first_token_seconds", "TTFT",
            ["model"], buckets=_TTFT_BUCKETS, registry=self.registry)
        self.itl = Histogram(
            f"{ns}_inter_token_latency_seconds", "ITL",
            ["model"], buckets=_ITL_BUCKETS, registry=self.registry)
        self.duration = Histogram(
            f"{ns}_request_duration_seconds", "Request duration",
            ["model", "endpoint"], buckets=_DUR_BUCKETS, registry=self.registry)
        self.input_tokens = Counter(
            f"{ns}_input_tokens_total", "Prompt tokens",
            ["model"], registry=self.registry)
        self.output_tokens = Counter(
            f"{ns}_output_tokens_total", "Generated tokens",
            ["model"], registry=self.registry)
        self.shed_total = Counter(
            f"{ns}_requests_shed_total",
            "Requests shed at admission (503) by overload protection",
            ["model", "endpoint", "reason"], registry=self.registry)

    def render(self) -> bytes:
        return generate_latest(self.registry)


class RequestTimer:
    """Tracks one request's TTFT/ITL/duration and reports on completion."""

    def __init__(self, metrics: FrontendMetrics, model: str, endpoint: str):
        self.m = metrics
        self.model = model
        self.endpoint = endpoint
        self.start = time.perf_counter()
        self.last_token: Optional[float] = None
        self.first_token: Optional[float] = None
        self._done = False
        self.m.inflight.labels(model).inc()

    def on_token(self, n: int = 1) -> None:
        if n <= 0:
            return  # role-only / finish-only chunks don't define TTFT
        now = time.perf_counter()
        if self.first_token is None:
            self.first_token = now
            self.m.ttft.labels(self.model).observe(now - self.start)
        elif self.last_token is not None and n:
            self.m.itl.labels(self.model).observe((now - self.last_token) / n)
        self.last_token = now
        if n:
            self.m.output_tokens.labels(self.model).inc(n)

    def done(self, status: str, prompt_tokens: int = 0) -> None:
        if self._done:  # idempotent: unwind paths may overlap
            return
        self._done = True
        self.m.inflight.labels(self.model).dec()
        self.m.requests_total.labels(self.model, self.endpoint, status).inc()
        self.m.duration.labels(self.model, self.endpoint).observe(
            time.perf_counter() - self.start)
        if prompt_tokens:
            self.m.input_tokens.labels(self.model).inc(prompt_tokens)


__all__ = ["FrontendMetrics", "RequestTimer"]
