"""Per-request Prometheus metrics for the HTTP frontend.

Parity: reference ``lib/llm/src/http/service/metrics.rs`` (~500 LoC): request
counters by model/endpoint/status, TTFT and inter-token-latency histograms,
inflight gauge, request duration.
"""

from __future__ import annotations

import time
from typing import Optional

from prometheus_client import (
    CollectorRegistry,
    Counter,
    Gauge,
    Histogram,
    generate_latest,
)

_TTFT_BUCKETS = (0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
                 5.0, 10.0, 30.0)
_ITL_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                0.5, 1.0)
_DUR_BUCKETS = (0.01, 0.05, 0.1, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0,
                300.0)
# stage spans range from sub-ms tokenize to multi-second prefill/decode
_STAGE_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                  0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0)


class StageMetrics:
    """``dynamo_tpu_stage_duration_seconds{stage}`` — per-stage request
    latency breakdown (queue|prefill|kv_transfer|decode|tokenize|detokenize),
    fed from locally-finished trace spans (``utils/tracing``).  Registered on
    BOTH the frontend registry and the worker system-server registry under
    the same name, so dashboards join one series across roles; each process
    observes only the spans it produced (adopted remote spans don't re-fire),
    so nothing double-counts."""

    def __init__(self, registry: Optional[CollectorRegistry] = None):
        self.duration = Histogram(
            "dynamo_tpu_stage_duration_seconds",
            "Per-stage request latency breakdown (trace-span stages)",
            ["stage"], buckets=_STAGE_BUCKETS, registry=registry)
        self._attached: set = set()

    def attach(self, tracer) -> None:
        """Observe this tracer's stage spans (idempotent per tracer)."""
        if id(tracer) in self._attached:
            return
        self._attached.add(id(tracer))
        tracer.add_listener(self._on_span)

    def detach(self, tracer) -> None:
        self._attached.discard(id(tracer))
        tracer.remove_listener(self._on_span)

    def _on_span(self, span) -> None:
        from dynamo_tpu.utils.tracing import STAGES
        if span.name in STAGES:
            self.duration.labels(span.name).observe(span.duration_s)


class FrontendMetrics:
    def __init__(self, registry: Optional[CollectorRegistry] = None,
                 slo_ttft_s: float = 0.0, slo_itl_s: float = 0.0):
        self.registry = registry or CollectorRegistry()
        # SLO targets for goodput accounting (0.0 = target disabled).
        # Judged per request at completion: TTFT against slo_ttft_s, the
        # request's WORST per-token gap against slo_itl_s.
        self.slo_ttft_s = float(slo_ttft_s)
        self.slo_itl_s = float(slo_itl_s)
        ns = "dynamo_frontend"
        self.requests_total = Counter(
            f"{ns}_requests_total", "HTTP requests",
            ["model", "endpoint", "status"], registry=self.registry)
        self.inflight = Gauge(
            f"{ns}_inflight_requests", "Concurrent requests",
            ["model"], registry=self.registry)
        self.ttft = Histogram(
            f"{ns}_time_to_first_token_seconds", "TTFT",
            ["model"], buckets=_TTFT_BUCKETS, registry=self.registry)
        self.itl = Histogram(
            f"{ns}_inter_token_latency_seconds", "ITL",
            ["model"], buckets=_ITL_BUCKETS, registry=self.registry)
        self.duration = Histogram(
            f"{ns}_request_duration_seconds", "Request duration",
            ["model", "endpoint"], buckets=_DUR_BUCKETS, registry=self.registry)
        self.input_tokens = Counter(
            f"{ns}_input_tokens_total", "Prompt tokens",
            ["model"], registry=self.registry)
        self.output_tokens = Counter(
            f"{ns}_output_tokens_total", "Generated tokens",
            ["model"], registry=self.registry)
        self.shed_total = Counter(
            f"{ns}_requests_shed_total",
            "Requests shed at admission (503) by overload protection",
            ["model", "endpoint", "reason"], registry=self.registry)
        # -- SLO / goodput ----------------------------------------------
        self.slo_total = Counter(
            f"{ns}_slo_total",
            "Per-request SLO judgments by target (ttft, itl) and outcome: "
            "'met'/'violated' judged at completion (itl against the "
            "request's WORST per-token gap), 'shed' counted at admission "
            "refusal — a shed request is an SLO miss the backlog never "
            "sees. Zero unless --slo-ttft-s/--slo-itl-s enable the target.",
            ["target", "outcome"], registry=self.registry)
        self.goodput_tokens = Counter(
            f"{ns}_goodput_tokens_total",
            "Generated tokens from requests that met EVERY enabled SLO "
            "target — goodput vs. raw dynamo_frontend_output_tokens_total "
            "throughput. Zero while no SLO target is configured.",
            ["model"], registry=self.registry)
        for target in ("ttft", "itl"):
            for outcome in ("met", "violated", "shed"):
                self.slo_total.labels(target, outcome)
        # per-stage latency breakdown from trace spans; HttpService attaches
        # the process tracer at start and detaches at stop
        self.stage = StageMetrics(self.registry)
        # failure-aware routing counters/gauges, sampled from the process-
        # wide RouterStats book at scrape time (routers live in ModelWatcher,
        # outside this registry's reach)
        self.router = RouterMetricsCollector(self.registry)

    def attach_coord(self, coord) -> "CoordClientMetrics":
        """Expose the process's coordinator-connection health next to the
        request metrics (``dynamo_coord_*`` series on the same /metrics)."""
        return CoordClientMetrics(coord, registry=self.registry)

    def record_slo_shed(self) -> None:
        """Count an admission-shed request against every enabled SLO
        target: the client saw a 503 instead of tokens, which is an SLO
        miss regardless of how fast the backlog would have drained."""
        if self.slo_ttft_s > 0:
            self.slo_total.labels("ttft", "shed").inc()
        if self.slo_itl_s > 0:
            self.slo_total.labels("itl", "shed").inc()

    def render(self) -> bytes:
        return generate_latest(self.registry)


class CoordClientMetrics:
    """Custom collector sampling a ``CoordClient``'s supervision state.

    Series: ``dynamo_coord_connected`` (gauge, 1 while the control-plane
    connection is up and resynced), ``dynamo_coord_reconnects_total`` /
    ``dynamo_coord_resyncs_total`` (counters), and
    ``dynamo_coord_last_outage_seconds`` (gauge, duration of the most recent
    survived outage). Sampled at scrape time — no wiring inside the client."""

    def __init__(self, coord, registry: Optional[CollectorRegistry] = None):
        self.coord = coord
        if registry is not None:
            registry.register(self)

    def collect(self):
        from prometheus_client.core import (
            CounterMetricFamily,
            GaugeMetricFamily,
        )
        yield GaugeMetricFamily(
            "dynamo_coord_connected",
            "1 while the coordinator connection is up and resynced",
            value=1.0 if self.coord.connected else 0.0)
        rec = CounterMetricFamily(
            "dynamo_coord_reconnects",
            "Coordinator connections re-established after an outage")
        rec.add_metric([], float(self.coord.reconnects_total))
        yield rec
        res = CounterMetricFamily(
            "dynamo_coord_resyncs",
            "State resync attempts after a reconnect (exceeds "
            "dynamo_coord_reconnects_total when resyncs are retried)")
        res.add_metric([], float(self.coord.resyncs_total))
        yield res
        yield GaugeMetricFamily(
            "dynamo_coord_last_outage_seconds",
            "Duration of the most recent survived coordinator outage",
            value=float(self.coord.last_outage_s))


class CoordinatorMetrics:
    """Custom collector sampling a server-side ``Coordinator`` (the
    replicated control-plane process itself, not a client of it).

    Series: ``dynamo_coord_role`` (1 acting primary / 0 standby /
    -1 deposed), ``dynamo_coord_failovers_total`` (promotions this process
    performed), ``dynamo_coord_replication_lag_ops`` (log entries queued to
    the slowest attached standby; 0 = caught up or none attached),
    ``dynamo_coord_standbys_attached`` and
    ``dynamo_coord_prefix_index_entries`` (live worker snapshots in the
    fleet KV prefix index).  Exposed by the standalone coordinator's
    system server (``DYN_SYSTEM_ENABLED=1``)."""

    _ROLES = {"primary": 1.0, "standby": 0.0, "deposed": -1.0}

    def __init__(self, coordinator, registry: Optional[CollectorRegistry] = None):
        self.coordinator = coordinator
        if registry is not None:
            registry.register(self)

    def collect(self):
        from prometheus_client.core import (
            CounterMetricFamily,
            GaugeMetricFamily,
        )
        c = self.coordinator
        yield GaugeMetricFamily(
            "dynamo_coord_role",
            "Replication role: 1 acting primary, 0 standby, -1 deposed",
            value=self._ROLES.get(c.role, -1.0))
        fo = CounterMetricFamily(
            "dynamo_coord_failovers",
            "Promotions to primary performed by this coordinator process")
        fo.add_metric([], float(c.failovers_total))
        yield fo
        yield GaugeMetricFamily(
            "dynamo_coord_replication_lag_ops",
            "Replication-log entries queued to the slowest attached "
            "standby (0 = fully caught up or no standby)",
            value=float(c.replication_lag_ops))
        yield GaugeMetricFamily(
            "dynamo_coord_standbys_attached",
            "Hot standbys currently attached to this coordinator",
            value=float(c.standbys_attached))
        yield GaugeMetricFamily(
            "dynamo_coord_prefix_index_entries",
            "Live worker holder-snapshots in the fleet-wide KV prefix "
            "index (kvstore/prefix_index/ entries whose TTL envelope has "
            "not expired; each is one worker's published block-hash set)",
            value=float(getattr(c, "prefix_index_entries", 0)))


class RouterMetricsCollector:
    """Custom collector over the process-wide failure-aware-routing book
    (``runtime/resilience.get_router_stats``).

    Series: ``dynamo_frontend_router_decisions_total{policy}``,
    ``dynamo_frontend_router_retries_total{reason}``,
    ``dynamo_frontend_router_hedges_total{outcome}``,
    ``dynamo_frontend_router_breaker_transitions_total{state}``,
    ``dynamo_frontend_router_breaker_state{instance}`` (0 closed /
    0.5 half-open / 1 open), ``dynamo_frontend_router_retry_budget_balance``,
    ``dynamo_frontend_router_retry_budget_exhausted_total``, and the
    NetKV pricing family: ``dynamo_frontend_router_net_priced_total``
    {outcome}, ``dynamo_frontend_router_net_cost_seconds_total`` and
    ``dynamo_frontend_router_net_priced_decisions_total``."""

    def __init__(self, registry: Optional[CollectorRegistry] = None):
        if registry is not None:
            registry.register(self)

    def collect(self):
        from prometheus_client.core import (
            CounterMetricFamily,
            GaugeMetricFamily,
        )
        from dynamo_tpu.runtime.resilience import get_router_stats
        s = get_router_stats()
        dec = CounterMetricFamily(
            "dynamo_frontend_router_decisions",
            "Routing decisions by policy", labels=["policy"])
        for policy, n in s.decisions.items():
            dec.add_metric([policy], float(n))
        yield dec
        ret = CounterMetricFamily(
            "dynamo_frontend_router_retries",
            "Re-dispatches (failover retries) by reason; 'denied' counts "
            "retries refused by the budget", labels=["reason"])
        for reason, n in s.retries.items():
            ret.add_metric([reason], float(n))
        yield ret
        hed = CounterMetricFamily(
            "dynamo_frontend_router_hedges",
            "Hedged dispatches by outcome "
            "(fired|won|lost|denied|expired)", labels=["outcome"])
        for outcome, n in s.hedges.items():
            hed.add_metric([outcome], float(n))
        yield hed
        tr = CounterMetricFamily(
            "dynamo_frontend_router_breaker_transitions",
            "Circuit-breaker state transitions by entered state",
            labels=["state"])
        for state, n in s.breaker_transitions.items():
            tr.add_metric([state], float(n))
        yield tr
        st = GaugeMetricFamily(
            "dynamo_frontend_router_breaker_state",
            "Per-instance breaker state: 0 closed, 0.5 half-open, 1 open",
            labels=["instance"])
        for iid, v in s.breaker_states.items():
            st.add_metric([iid], v)
        yield st
        yield GaugeMetricFamily(
            "dynamo_frontend_router_retry_budget_balance",
            "Retry-budget tokens currently available",
            value=float(s.budget_balance))
        ex = CounterMetricFamily(
            "dynamo_frontend_router_retry_budget_exhausted",
            "Retry/hedge attempts refused because the budget was empty")
        ex.add_metric([], float(s.budget_exhausted))
        yield ex
        np_ = CounterMetricFamily(
            "dynamo_frontend_router_net_priced",
            "KV routing decisions where a fleet-held prefix was priced "
            "against the measured kv_transfer bandwidth, by outcome: "
            "'credit' (transfer beats recompute), 'no_credit' (recompute "
            "wins), 'no_path' (no bandwidth ever measured)",
            labels=["outcome"])
        for outcome in ("credit", "no_credit", "no_path"):
            np_.add_metric([outcome], float(s.net_priced.get(outcome, 0)))
        yield np_
        nc = CounterMetricFamily(
            "dynamo_frontend_router_net_cost_seconds",
            "Estimated KV-transfer seconds behind net-priced decisions "
            "(est_transfer_bytes / plane bandwidth EWMA); _count is the "
            "decisions priced")
        nc.add_metric([], float(s.net_cost_seconds_sum))
        yield nc
        ncc = CounterMetricFamily(
            "dynamo_frontend_router_net_priced_decisions",
            "Net-priced decisions counted into "
            "dynamo_frontend_router_net_cost_seconds")
        ncc.add_metric([], float(s.net_cost_seconds_count))
        yield ncc


class RequestTimer:
    """Tracks one request's TTFT/ITL/duration and reports on completion."""

    def __init__(self, metrics: FrontendMetrics, model: str, endpoint: str):
        self.m = metrics
        self.model = model
        self.endpoint = endpoint
        self.start = time.perf_counter()
        self.last_token: Optional[float] = None
        self.first_token: Optional[float] = None
        self._done = False
        self._ntokens = 0
        self._itl_max_s: Optional[float] = None
        self.m.inflight.labels(model).inc()

    def on_token(self, n: int = 1) -> None:
        if n <= 0:
            return  # role-only / finish-only chunks don't define TTFT
        now = time.perf_counter()
        if self.first_token is None:
            self.first_token = now
            self.m.ttft.labels(self.model).observe(now - self.start)
        elif self.last_token is not None and n:
            itl = (now - self.last_token) / n
            self.m.itl.labels(self.model).observe(itl)
            if self._itl_max_s is None or itl > self._itl_max_s:
                self._itl_max_s = itl
        self.last_token = now
        if n:
            self._ntokens += n
            self.m.output_tokens.labels(self.model).inc(n)

    def done(self, status: str, prompt_tokens: int = 0) -> None:
        if self._done:  # idempotent: unwind paths may overlap
            return
        self._done = True
        self.m.inflight.labels(self.model).dec()
        self.m.requests_total.labels(self.model, self.endpoint, status).inc()
        self.m.duration.labels(self.model, self.endpoint).observe(
            time.perf_counter() - self.start)
        if prompt_tokens:
            self.m.input_tokens.labels(self.model).inc(prompt_tokens)
        # SLO judgment + goodput: only requests that produced tokens are
        # judged (an errored stream with no first token has nothing to
        # measure and contributes zero goodput either way)
        slo_ok = True
        judged = False
        if self.m.slo_ttft_s > 0 and self.first_token is not None:
            met = (self.first_token - self.start) <= self.m.slo_ttft_s
            self.m.slo_total.labels(
                "ttft", "met" if met else "violated").inc()
            slo_ok = slo_ok and met
            judged = True
        if self.m.slo_itl_s > 0 and self._itl_max_s is not None:
            met = self._itl_max_s <= self.m.slo_itl_s
            self.m.slo_total.labels(
                "itl", "met" if met else "violated").inc()
            slo_ok = slo_ok and met
            judged = True
        if judged and slo_ok and self._ntokens:
            self.m.goodput_tokens.labels(self.model).inc(self._ntokens)


__all__ = ["FrontendMetrics", "CoordClientMetrics", "CoordinatorMetrics",
           "RequestTimer", "RouterMetricsCollector", "StageMetrics"]
