"""Typed OpenAI HTTP client for dynamo_tpu frontends.

Parity: reference ``lib/llm/src/http/client.rs`` (typed
chat/completions/models client with SSE streaming and aggregation) — the
piece round-1 tests hand-rolled with raw aiohttp calls.

Responses parse into the same pydantic models the server serializes
(``protocols/openai.py``), so client code gets attribute access and
validation instead of dict spelunking:

    async with OpenAIClient("http://host:8080") as c:
        resp = await c.chat([{"role": "user", "content": "hi"}],
                            model="llama", max_tokens=32)
        async for chunk in c.chat_stream([...], model="llama"):
            ...
"""

from __future__ import annotations

import json
from typing import Any, AsyncIterator, Dict, List, Optional

import aiohttp

from dynamo_tpu.protocols.openai import (
    ChatCompletionChunk,
    ChatCompletionResponse,
    CompletionResponse,
    EmbeddingResponse,
    ModelList,
)


class HttpClientError(RuntimeError):
    """Non-2xx response; carries status and the server's error body."""

    def __init__(self, status: int, body: Any):
        self.status = status
        self.body = body
        message = body
        if isinstance(body, dict):
            message = (body.get("error") or {}).get("message", body)
        super().__init__(f"HTTP {status}: {message}")


class OpenAIClient:
    """Async typed client over one frontend base URL."""

    def __init__(self, base_url: str,
                 timeout: Optional[float] = 300.0):
        self.base = base_url.rstrip("/")
        self._timeout = aiohttp.ClientTimeout(total=timeout)
        self._session: Optional[aiohttp.ClientSession] = None

    async def __aenter__(self) -> "OpenAIClient":
        self._session = aiohttp.ClientSession(timeout=self._timeout)
        return self

    async def __aexit__(self, *exc) -> None:
        await self.close()

    async def close(self) -> None:
        if self._session is not None:
            await self._session.close()
            self._session = None

    def _s(self) -> aiohttp.ClientSession:
        if self._session is None:
            self._session = aiohttp.ClientSession(timeout=self._timeout)
        return self._session

    async def _post_json(self, path: str, body: Dict[str, Any]) -> Any:
        async with self._s().post(self.base + path, json=body) as r:
            payload = await r.json(content_type=None)
            if r.status // 100 != 2:
                raise HttpClientError(r.status, payload)
            return payload

    # -- surfaces ----------------------------------------------------------

    async def models(self) -> ModelList:
        async with self._s().get(self.base + "/v1/models") as r:
            payload = await r.json(content_type=None)
            if r.status // 100 != 2:
                raise HttpClientError(r.status, payload)
            return ModelList.model_validate(payload)

    async def health(self) -> Dict[str, Any]:
        async with self._s().get(self.base + "/health") as r:
            return await r.json(content_type=None)

    async def chat(self, messages: List[Dict[str, Any]], *, model: str,
                   **params) -> ChatCompletionResponse:
        body = {"model": model, "messages": messages, "stream": False,
                **params}
        return ChatCompletionResponse.model_validate(
            await self._post_json("/v1/chat/completions", body))

    async def chat_stream(self, messages: List[Dict[str, Any]], *,
                          model: str, **params
                          ) -> AsyncIterator[ChatCompletionChunk]:
        body = {"model": model, "messages": messages, "stream": True,
                **params}
        async with self._s().post(self.base + "/v1/chat/completions",
                                  json=body) as r:
            if r.status // 100 != 2:
                raise HttpClientError(r.status,
                                      await r.json(content_type=None))
            async for data in _sse_data(r):
                yield ChatCompletionChunk.model_validate(data)

    async def completion(self, prompt: str, *, model: str,
                         **params) -> CompletionResponse:
        body = {"model": model, "prompt": prompt, "stream": False, **params}
        return CompletionResponse.model_validate(
            await self._post_json("/v1/completions", body))

    async def completion_stream(self, prompt: str, *, model: str, **params
                                ) -> AsyncIterator[CompletionResponse]:
        body = {"model": model, "prompt": prompt, "stream": True, **params}
        async with self._s().post(self.base + "/v1/completions",
                                  json=body) as r:
            if r.status // 100 != 2:
                raise HttpClientError(r.status,
                                      await r.json(content_type=None))
            async for data in _sse_data(r):
                yield CompletionResponse.model_validate(data)

    async def embeddings(self, inputs, *, model: str,
                         **params) -> EmbeddingResponse:
        body = {"model": model, "input": inputs, **params}
        return EmbeddingResponse.model_validate(
            await self._post_json("/v1/embeddings", body))


async def _sse_data(resp: aiohttp.ClientResponse) -> AsyncIterator[Any]:
    """Decode `data:` SSE lines until [DONE]; surfaces in-stream errors."""
    async for raw in resp.content:
        line = raw.decode("utf-8", errors="replace").strip()
        if not line.startswith("data:"):
            continue
        payload = line[5:].strip()
        if payload == "[DONE]":
            return
        data = json.loads(payload)
        if isinstance(data, dict) and "error" in data:
            raise HttpClientError(resp.status, data)
        yield data


__all__ = ["OpenAIClient", "HttpClientError"]
