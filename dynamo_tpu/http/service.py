"""OpenAI-compatible HTTP service (aiohttp).

Parity: reference ``lib/llm/src/http/service/`` (axum): ``/v1/chat/completions``,
``/v1/completions``, ``/v1/models``, ``/health``, ``/live``, ``/metrics``,
``/clear_kv_blocks``; SSE streaming with client-disconnect detection; stream
aggregation for non-streaming requests; per-request Prometheus metrics.
"""

from __future__ import annotations

import asyncio
import json
import logging
import math
import time
from typing import Any, Dict, List, Optional

from aiohttp import web
from pydantic import ValidationError

from dynamo_tpu.http.metrics import FrontendMetrics, RequestTimer
from dynamo_tpu.llm.model_manager import ModelManager
from dynamo_tpu.protocols import sse
from dynamo_tpu.protocols.common import FinishReason
from dynamo_tpu.runtime.rpc import DeadlineExceededError
from dynamo_tpu.runtime.system_server import (
    trace_get_response,
    trace_list_response,
)
from dynamo_tpu.utils.tracing import get_tracer
from dynamo_tpu.protocols.openai import (
    ChatChoice,
    ChatCompletionRequest,
    ChatCompletionResponse,
    ChatMessage,
    ChoiceLogprobs,
    CompletionChoice,
    CompletionRequest,
    CompletionResponse,
    ModelInfo,
    ModelList,
    Usage,
    new_request_id,
    now_unix,
)

logger = logging.getLogger(__name__)

# upper bound on the OpenAI `n` parameter (choices per request): each
# choice is an independent engine generation — unbounded n would be a
# one-request DoS on scheduler admission
MAX_CHOICES = 16


def _legacy_logprobs(entries: List[dict], offset_start: int = 0):
    """Chat-style logprob entries -> the legacy completions logprobs object
    (tokens / token_logprobs / top_logprobs / text_offset). Returns the
    object and the next character offset (streaming keeps it cumulative)."""
    out = {"tokens": [], "token_logprobs": [], "top_logprobs": [],
           "text_offset": []}
    off = offset_start
    for e in entries:
        out["tokens"].append(e["token"])
        out["token_logprobs"].append(e["logprob"])
        out["top_logprobs"].append(
            {t["token"]: t["logprob"]
             for t in e.get("top_logprobs", [])} or None)
        out["text_offset"].append(off)
        off += len(e["token"])
    return out, off


def _merge_choice_usage(usage: "Usage", u: "Usage", i: int) -> None:
    """Fold one choice's usage into the request total: prompt tokens count
    ONCE, completion tokens sum, and prompt-caching details come from
    CHOICE 0 only — later concurrent choices hit the prefix cache choice 0
    just populated, which would claim a cold prompt was served cached."""
    usage.prompt_tokens = u.prompt_tokens
    usage.completion_tokens += u.completion_tokens
    if i == 0 and u.prompt_tokens_details is not None:
        usage.prompt_tokens_details = u.prompt_tokens_details


def _error(status: int, message: str, etype: str = "invalid_request_error") -> web.Response:
    return web.json_response(
        {"error": {"message": message, "type": etype, "code": status}},
        status=status)


async def _sse_error(resp: web.StreamResponse, exc: Exception,
                     err_type: str) -> None:
    """Terminal SSE error event + [DONE] — once streaming has begun the 200
    status line is already on the wire, so errors ride the event stream."""
    await resp.write(sse.encode_data(
        {"error": {"message": str(exc), "type": err_type}}))
    await resp.write(sse.encode_done())


class HttpService:
    """The frontend HTTP server; routes into a ModelManager's pipelines."""

    def __init__(self, manager: ModelManager, host: str = "0.0.0.0",
                 port: int = 8080, metrics: Optional[FrontendMetrics] = None,
                 request_timeout_s: float = 0.0,
                 max_inflight: int = 0, max_model_inflight: int = 0,
                 shed_retry_after_s: float = 1.0,
                 slo_ttft_s: float = 0.0, slo_itl_s: float = 0.0):
        self.manager = manager
        self.host = host
        self.port = port
        self.metrics = metrics or FrontendMetrics(
            slo_ttft_s=slo_ttft_s, slo_itl_s=slo_itl_s)
        # SLO targets apply to a caller-supplied FrontendMetrics too —
        # the service flags are authoritative when set
        if slo_ttft_s > 0:
            self.metrics.slo_ttft_s = float(slo_ttft_s)
        if slo_itl_s > 0:
            self.metrics.slo_itl_s = float(slo_itl_s)
        # request-lifecycle robustness knobs (see utils/config.RuntimeConfig):
        # default end-to-end deadline (0 = none) and overload high-water
        # marks (0 = unlimited) for total / per-model concurrent requests
        self.request_timeout_s = request_timeout_s
        self.max_inflight = max_inflight
        self.max_model_inflight = max_model_inflight
        self.shed_retry_after_s = shed_retry_after_s
        self._inflight_total = 0
        self._inflight_by_model: Dict[str, int] = {}
        self.app = web.Application(client_max_size=64 * 1024 * 1024)
        self.app.router.add_post("/v1/chat/completions", self.handle_chat)
        self.app.router.add_post("/v1/responses", self.handle_responses)
        self.app.router.add_post("/v1/completions", self.handle_completions)
        self.app.router.add_post("/v1/embeddings", self.handle_embeddings)
        self.app.router.add_get("/v1/models", self.handle_models)
        self.app.router.add_get("/health", self.handle_health)
        self.app.router.add_get("/live", self.handle_live)
        self.app.router.add_get("/healthz", self.handle_live)
        self.app.router.add_get("/healthz/ready", self.handle_ready)
        self.app.router.add_get("/metrics", self.handle_metrics)
        self.app.router.add_get("/v1/traces", self.handle_traces)
        self.app.router.add_get("/v1/traces/{trace_id}", self.handle_trace)
        self.app.router.add_post("/clear_kv_blocks", self.handle_clear_kv)
        self._runner: Optional[web.AppRunner] = None
        self._clear_kv_hook = None  # async () -> dict
        # the process's CoordClient (attach_coord): /healthz/ready turns
        # 503 while its supervised connection is down, so load balancers
        # drain traffic away from a control-plane outage
        self._coord = None
        # the process tracer: every request opens a root span here; the
        # flight recorder behind /v1/traces and the per-stage histogram
        # (metrics.stage) both hang off it
        self.tracer = get_tracer()
        if not self.tracer.service:
            self.tracer.service = "frontend"

    async def start(self) -> "HttpService":
        self.metrics.stage.attach(self.tracer)
        self._runner = web.AppRunner(self.app, access_log=None)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()
        if self.port == 0:
            self.port = self._runner.addresses[0][1]
        logger.info("http service on %s:%d", self.host, self.port)
        return self

    async def stop(self) -> None:
        self.metrics.stage.detach(self.tracer)
        if self._runner is not None:
            await self._runner.cleanup()

    # -- handlers ----------------------------------------------------------

    async def handle_health(self, request: web.Request) -> web.Response:
        return web.json_response({
            "status": "healthy" if self.manager.names() else "no_models",
            "models": self.manager.names()})

    async def handle_live(self, request: web.Request) -> web.Response:
        return web.json_response({"live": True})

    def attach_coord(self, coord) -> "object":
        """Wire the process's ``CoordClient`` into this service: its
        connection health gates ``GET /healthz/ready`` and its supervision
        counters join /metrics (``dynamo_coord_*``).  Returns the metrics
        collector for symmetry with ``FrontendMetrics.attach_coord``."""
        self._coord = coord
        return self.metrics.attach_coord(coord)

    async def handle_ready(self, request: web.Request) -> web.Response:
        """Readiness (vs. /healthz liveness, always 200): 503 while the
        coordinator connection is down — discovery is frozen, so new
        requests would only pile onto stale routing state."""
        from dynamo_tpu.runtime.system_server import coord_ready_reasons
        reasons = coord_ready_reasons(self._coord)
        if not self.manager.names():
            reasons.append("no models registered")
        ready = not reasons
        return web.json_response(
            {"ready": ready, "reasons": reasons,
             "models": self.manager.names()},
            status=200 if ready else 503)

    async def handle_metrics(self, request: web.Request) -> web.Response:
        return web.Response(body=self.metrics.render(),
                            content_type="text/plain", charset="utf-8")

    async def handle_models(self, request: web.Request) -> web.Response:
        models = ModelList(data=[
            ModelInfo(id=name, created=now_unix()) for name in self.manager.names()])
        return web.json_response(models.model_dump())

    async def handle_traces(self, request: web.Request) -> web.Response:
        return trace_list_response(self.tracer, request)

    async def handle_trace(self, request: web.Request) -> web.Response:
        return trace_get_response(self.tracer,
                                  request.match_info["trace_id"])

    async def handle_clear_kv(self, request: web.Request) -> web.Response:
        if self._clear_kv_hook is None:
            return web.json_response({"cleared": []})
        return web.json_response(await self._clear_kv_hook())

    def set_clear_kv_hook(self, hook) -> None:
        self._clear_kv_hook = hook

    @staticmethod
    def _stamp_rid(resp: web.StreamResponse,
                   request_id: str) -> web.StreamResponse:
        """X-Request-Id on an unprepared response (streamed responses set
        it in their constructor headers — after ``prepare`` it's too
        late)."""
        if not resp.prepared:
            resp.headers["X-Request-Id"] = request_id
        return resp

    # -- overload shedding + deadlines -------------------------------------

    def _shed_or_admit(self, model: str,
                       endpoint: str) -> Optional[web.Response]:
        """Admission control: returns a 503 + Retry-After response when a
        high-water mark is hit, else admits (callers MUST pair with
        ``_release`` in a finally).  Shed requests are counted in
        ``dynamo_frontend_requests_shed_total``."""
        if self.max_inflight and self._inflight_total >= self.max_inflight:
            reason = "inflight_high_water"
        elif (self.max_model_inflight
              and self._inflight_by_model.get(model, 0)
              >= self.max_model_inflight):
            reason = "model_inflight_high_water"
        else:
            self._inflight_total += 1
            self._inflight_by_model[model] = \
                self._inflight_by_model.get(model, 0) + 1
            return None
        self.metrics.shed_total.labels(model, endpoint, reason).inc()
        self.metrics.requests_total.labels(model, endpoint, "503").inc()
        # a shed request is an SLO miss for goodput accounting — the
        # client got a 503 instead of tokens
        self.metrics.record_slo_shed()
        resp = _error(503, "server overloaded; retry later", "overloaded")
        resp.headers["Retry-After"] = str(
            max(1, math.ceil(self.shed_retry_after_s)))
        return resp

    def _release(self, model: str) -> None:
        self._inflight_total = max(0, self._inflight_total - 1)
        n = self._inflight_by_model.get(model, 0) - 1
        if n <= 0:
            self._inflight_by_model.pop(model, None)
        else:
            self._inflight_by_model[model] = n

    def _resolve_deadline(self, http_req: web.Request,
                          nvext=None) -> Optional[float]:
        """Absolute unix deadline for a request: per-request override
        (``nvext.timeout_s``, then the ``X-Request-Timeout`` header, seconds)
        falling back to the configured service default; None = no deadline.
        Raises ValueError (-> 400) on a malformed or non-positive override."""
        timeout: Optional[float] = None
        if nvext is not None and getattr(nvext, "timeout_s", None) is not None:
            timeout = float(nvext.timeout_s)
        else:
            hdr = http_req.headers.get("X-Request-Timeout")
            if hdr is not None:
                try:
                    timeout = float(hdr)
                except ValueError:
                    raise ValueError(
                        f"invalid X-Request-Timeout header: {hdr!r}") from None
        if timeout is not None and (not math.isfinite(timeout)
                                    or timeout <= 0):
            # JSON NaN/Infinity parse fine and would defeat the deadline
            raise ValueError("request timeout must be positive and finite")
        if timeout is None:
            timeout = self.request_timeout_s
        if not timeout or timeout <= 0:
            return None
        return time.time() + timeout

    async def handle_embeddings(self, request: web.Request) -> web.Response:
        from dynamo_tpu.protocols.openai import (
            EmbeddingData, EmbeddingRequest, EmbeddingResponse)
        try:
            req = EmbeddingRequest.model_validate(await request.json())
        except (ValidationError, json.JSONDecodeError, UnicodeDecodeError) as e:
            return _error(400, f"invalid request: {e}")
        pipeline = self.manager.get(req.model)
        if pipeline is None:
            return _error(404, f"model {req.model!r} not found")
        if req.dimensions is not None and req.dimensions <= 0:
            # before the forward pass — an invalid ask must not pay for
            # the model compute it then discards
            return _error(400, "dimensions must be positive")
        shed = self._shed_or_admit(req.model, "embeddings")
        if shed is not None:
            return shed
        request_id = new_request_id("embd")
        try:
            vectors, prompt_tokens = await pipeline.generate_embeddings(req)
        except NotImplementedError as e:
            return self._stamp_rid(_error(501, str(e)), request_id)
        except Exception as e:  # noqa: BLE001
            logger.exception("embeddings failed")
            return self._stamp_rid(_error(500, str(e), "internal_error"),
                                   request_id)
        finally:
            self._release(req.model)
        if req.dimensions is not None and vectors:
            if req.dimensions > len(vectors[0]):
                return self._stamp_rid(_error(
                    400, f"dimensions={req.dimensions} exceeds the "
                         f"model's embedding width {len(vectors[0])}"),
                    request_id)
            # OpenAI-style dimensionality reduction: truncate (vectors are
            # mean-pooled hidden states, not unit-norm — no renormalize)
            vectors = [v[:req.dimensions] for v in vectors]
        if req.encoding_format == "base64":
            # the official openai client requests base64 BY DEFAULT and
            # decodes little-endian float32 bytes
            import base64

            import numpy as _np
            vectors = [base64.b64encode(
                _np.asarray(v, _np.dtype("<f4")).tobytes()).decode()
                for v in vectors]
        resp = EmbeddingResponse(
            data=[EmbeddingData(index=i, embedding=v)
                  for i, v in enumerate(vectors)],
            model=req.model,
            usage=Usage(prompt_tokens=prompt_tokens,
                        total_tokens=prompt_tokens))
        return self._stamp_rid(
            web.json_response(resp.model_dump(exclude_none=True)),
            request_id)

    async def handle_chat(self, request: web.Request) -> web.StreamResponse:
        try:
            req = ChatCompletionRequest.model_validate(await request.json())
        except (ValidationError, json.JSONDecodeError, UnicodeDecodeError) as e:
            return _error(400, f"invalid request: {e}")
        pipeline = self.manager.get(req.model)
        if pipeline is None:
            return _error(404, f"model {req.model!r} not found", "model_not_found")
        if not 1 <= req.n <= MAX_CHOICES:
            return _error(400, f"n must be between 1 and {MAX_CHOICES}")
        try:
            deadline = self._resolve_deadline(request, req.nvext)
        except ValueError as e:
            return _error(400, str(e))
        shed = self._shed_or_admit(req.model, "chat")
        if shed is not None:
            return shed
        # the frontend mints the request id ONCE: it rides every RPC hop's
        # headers (so worker logs/counters see the same id), names the root
        # trace span, and returns to the client as X-Request-Id
        request_id = new_request_id()
        timer = RequestTimer(self.metrics, req.model, "chat")
        root = self.tracer.start_trace("http_request", attrs={
            "request_id": request_id, "model": req.model,
            "endpoint": "chat"})
        try:
            if req.stream:
                return await self._stream_chat(request, req, pipeline,
                                               request_id, timer, deadline)
            return self._stamp_rid(await self._aggregate_chat(
                req, pipeline, request_id, timer, deadline), request_id)
        except ValueError as e:
            timer.done("400")
            root.set_error(str(e))
            return self._stamp_rid(_error(400, str(e)), request_id)
        except DeadlineExceededError as e:
            timer.done("504")
            root.set_error(str(e))
            return self._stamp_rid(_error(504, str(e), "deadline_exceeded"),
                                   request_id)
        except ConnectionResetError:
            timer.done("499")  # client went away mid-write
            root.set_error("client disconnected")
            raise
        except ConnectionError as e:
            timer.done("503")
            root.set_error(str(e))
            return self._stamp_rid(
                _error(503, str(e), "service_unavailable"), request_id)
        except asyncio.CancelledError:
            timer.done("499")
            root.set_error("cancelled")
            raise
        except Exception as e:
            logger.exception("chat handler error")
            timer.done("500")
            root.set_error(str(e))
            return self._stamp_rid(_error(500, str(e), "internal_error"),
                                   request_id)
        finally:
            self._release(req.model)
            root.finish()

    async def _stream_chat(self, http_req: web.Request,
                           req: ChatCompletionRequest, pipeline,
                           request_id: str, timer: RequestTimer,
                           deadline: Optional[float] = None
                           ) -> web.StreamResponse:
        # preprocess before preparing the response so validation errors can
        # still produce a clean HTTP 400
        preprocessed, delta = pipeline.prepare_chat(req, request_id,
                                                    deadline_unix=deadline)
        annotation_only = pipeline.resolve_annotations(preprocessed)
        resp = web.StreamResponse(headers={
            "Content-Type": "text/event-stream",
            "Cache-Control": "no-cache",
            "Connection": "keep-alive",
            "X-Request-Id": request_id})
        await resp.prepare(http_req)
        if annotation_only:
            # e.g. query_instance_id: answer with the annotation events and
            # no generation (parity: reference annotation short-circuit)
            for name, value in preprocessed.annotations_payload.items():
                await resp.write(sse.SseEvent(
                    event=name,
                    data=json.dumps(value, separators=(",", ":"))).encode())
            await resp.write(sse.encode_done())
            timer.done("200", prompt_tokens=len(preprocessed.token_ids))
            await resp.write_eof()
            return resp
        status = "200"
        include_usage = bool(req.stream_options and req.stream_options.include_usage)
        if max(1, req.n or 1) > 1:
            return await self._stream_chat_multi(
                resp, req, pipeline, request_id, timer,
                (preprocessed, delta), include_usage, deadline)
        gen = pipeline.run_chat(preprocessed, delta)
        emitted_tokens = 0
        try:
            # requested annotations (formatted_prompt, token_ids, ...) ride as
            # named SSE events ahead of the deltas (parity: nvext annotations)
            for name, value in preprocessed.annotations_payload.items():
                await resp.write(sse.SseEvent(
                    event=name,
                    data=json.dumps(value, separators=(",", ":"))).encode())
            # tool-call extraction needs the COMPLETE message, which only
            # exists when the finish chunk arrives — so with tools active
            # the finish chunk (and anything after it, e.g. the usage
            # chunk) is HELD, and flushed at stream end with its
            # finish_reason rewritten to "tool_calls" + the parsed
            # delta.tool_calls when the text parses as calls. The client
            # sees exactly one finish_reason, agreeing with the aggregated
            # path; text deltas stream untouched either way.
            match_tools = bool(req.tools) and req.tool_choice != "none"
            stream_text: List[str] = []
            held: List[dict] = []
            async for chunk in gen:
                if chunk.usage is not None and not chunk.choices:
                    if not include_usage:
                        continue  # client didn't opt into the usage chunk
                if match_tools:
                    for choice in chunk.choices:
                        if choice.delta.content:
                            stream_text.append(choice.delta.content)
                # token accounting from the delta generator's counter (a chunk
                # may carry text from several tokens; chunks != tokens)
                timer.on_token(delta.completion_tokens - emitted_tokens)
                emitted_tokens = delta.completion_tokens
                payload = chunk.model_dump(exclude_none=True)
                if match_tools and (held or any(
                        c.finish_reason for c in chunk.choices)):
                    held.append(payload)
                    continue
                await resp.write(sse.encode_data(payload))
            if match_tools:
                from dynamo_tpu.preprocessor.tools import parse_tool_calls
                calls = parse_tool_calls("".join(stream_text),
                                         req.tool_choice or "auto")
                if calls and held:
                    for choice in held[0].get("choices", []):
                        if choice.get("finish_reason"):
                            choice["finish_reason"] = "tool_calls"
                            choice.setdefault("delta", {})["tool_calls"] = \
                                calls
                for payload in held:
                    await resp.write(sse.encode_data(payload))
            await resp.write(sse.encode_done())
        except (ConnectionResetError, asyncio.CancelledError):
            # client disconnected: stop generating (parity: disconnect.rs)
            status = "499"
            raise
        except DeadlineExceededError as e:
            # mid-stream deadline: a clean typed SSE error, no migration
            # replay (the router never saw a connection-shaped failure)
            status = "504"
            await _sse_error(resp, e, "deadline_exceeded")
        except Exception as e:
            logger.exception("stream error for %s", request_id)
            status = "500"
            await _sse_error(resp, e, "internal_error")
        finally:
            await gen.aclose()
            timer.done(status)
            if status not in ("200",):
                sp = self.tracer.current_span()
                if sp is not None:
                    sp.set_error(f"stream ended with status {status}")
        await resp.write_eof()
        return resp

    async def _stream_chat_multi(self, resp, req, pipeline,
                                 request_id: str, timer: RequestTimer,
                                 first_prepared, include_usage: bool,
                                 deadline: Optional[float] = None):
        """n > 1 streaming: the n choice generators run concurrently and
        their chunks interleave on one SSE stream, each rewritten to its
        choice index (standard OpenAI multi-choice streaming). Tool-call
        extraction is n==1-only (the single-finish-chunk rewrite does not
        compose with interleaved choices); tool-JSON streams as text here.
        Per-choice usage chunks aggregate into ONE final usage chunk."""
        n = req.n
        pairs = [first_prepared] + [
            self._prepare_choice(req, pipeline, request_id, i, deadline)
            for i in range(1, n)]
        # requested annotations ride ahead of the deltas, same as n == 1
        for name, value in first_prepared[0].annotations_payload.items():
            await resp.write(sse.SseEvent(
                event=name,
                data=json.dumps(value, separators=(",", ":"))).encode())
        # bounded: the pumps await put() when the client reads slowly, so
        # generation paces to the SSE write rate instead of accumulating
        # chunks without backpressure (ADVICE r4; matches the n==1 path's
        # implicit pacing). 8 chunks/choice of slack keeps the choices
        # interleaving without coupling their schedulers.
        queue: asyncio.Queue = asyncio.Queue(maxsize=8 * n)

        async def pump(i, pre, d):
            gen = pipeline.run_chat(pre, d)
            try:
                try:
                    async for chunk in gen:
                        await queue.put((i, chunk))
                finally:
                    await gen.aclose()
                await queue.put((i, None))
            except asyncio.CancelledError:
                # the consumer cancelled us (client gone): it will never
                # get() again, so a sentinel put on the now-bounded queue
                # could block forever — skip it and exit cancelled
                raise
            except Exception as e:  # noqa: BLE001 — surface per stream
                await queue.put((i, e))

        tasks = [asyncio.create_task(pump(i, pre, d))
                 for i, (pre, d) in enumerate(pairs)]
        status = "200"
        usage = Usage()
        emitted = [0] * n
        try:
            live = n
            while live:
                i, chunk = await queue.get()
                if chunk is None:
                    live -= 1
                    continue
                if isinstance(chunk, Exception):
                    raise chunk
                if chunk.usage is not None and not chunk.choices:
                    _merge_choice_usage(usage, chunk.usage, i)
                    continue
                # token accounting from stream i's delta counter (a chunk
                # may carry several tokens; chunks != tokens)
                d = pairs[i][1]
                timer.on_token(d.completion_tokens - emitted[i])
                emitted[i] = d.completion_tokens
                payload = chunk.model_dump(exclude_none=True)
                payload["id"] = request_id
                for c in payload.get("choices", []):
                    c["index"] = i
                await resp.write(sse.encode_data(payload))
            if include_usage:
                usage.total_tokens = (usage.prompt_tokens
                                      + usage.completion_tokens)
                await resp.write(sse.encode_data({
                    "id": request_id, "object": "chat.completion.chunk",
                    "created": now_unix(), "model": req.model,
                    "choices": [],
                    "usage": usage.model_dump(exclude_none=True)}))
            await resp.write(sse.encode_done())
        except (ConnectionResetError, asyncio.CancelledError):
            status = "499"
            raise
        except DeadlineExceededError as e:
            status = "504"
            await _sse_error(resp, e, "deadline_exceeded")
        except Exception as e:  # noqa: BLE001
            logger.exception("multi-choice stream error for %s", request_id)
            status = "500"
            await _sse_error(resp, e, "internal_error")
        finally:
            for t in tasks:
                t.cancel()
            timer.done(status)
        await resp.write_eof()
        return resp

    @staticmethod
    def _choice_identity(request_id: str, seed, index: int):
        """(rid, seed) for choice ``index`` of an n-way request — ONE
        convention for chat and legacy completions: distinct engine
        request ids keep the n generations independent, and a seeded
        request offsets the seed per choice so choices differ while each
        remains reproducible."""
        rid = request_id if index == 0 else f"{request_id}-c{index}"
        return rid, (seed + index if seed is not None and index else seed)

    def _prepare_choice(self, req, pipeline, request_id: str, index: int,
                        deadline: Optional[float] = None):
        """(preprocessed, delta) for choice ``index`` of an n-way chat."""
        rid, seed = self._choice_identity(request_id, req.seed, index)
        preprocessed, delta = pipeline.prepare_chat(req, rid,
                                                    deadline_unix=deadline)
        preprocessed.sampling_options.seed = seed
        return preprocessed, delta

    async def _collect_chat(self, req: ChatCompletionRequest, pipeline,
                            request_id: str, timer: RequestTimer,
                            prepared=None, deadline: Optional[float] = None):
        """Drain the chunk stream; returns (text, finish_reason,
        lp_entries, usage) — shared by the aggregated chat response and
        the /v1/responses bridge."""
        text_parts: List[str] = []
        lp_entries: List[dict] = []
        finish_reason: Optional[str] = None
        usage = Usage()
        preprocessed, delta = (prepared if prepared is not None
                               else pipeline.prepare_chat(
                                   req, request_id, deadline_unix=deadline))
        gen = pipeline.run_chat(preprocessed, delta)
        emitted_tokens = 0
        try:
            async for chunk in gen:
                for choice in chunk.choices:
                    if choice.delta.content:
                        text_parts.append(choice.delta.content)
                    if choice.logprobs and choice.logprobs.content:
                        lp_entries.extend(choice.logprobs.content)
                    if choice.finish_reason:
                        finish_reason = choice.finish_reason
                if chunk.usage is not None:
                    usage = chunk.usage
                timer.on_token(delta.completion_tokens - emitted_tokens)
                emitted_tokens = delta.completion_tokens
        finally:
            await gen.aclose()
        return "".join(text_parts), finish_reason, lp_entries, usage

    async def _aggregate_chat(self, req: ChatCompletionRequest, pipeline,
                              request_id: str, timer: RequestTimer,
                              deadline: Optional[float] = None
                              ) -> web.Response:
        """Aggregate the chunk stream into one response (parity:
        ``protocols/openai/chat_completions/aggregator.rs``); ``n > 1``
        runs the choices CONCURRENTLY (the engine batches them like any
        other traffic, sharing the prompt via the prefix cache)."""
        n = max(1, req.n or 1)
        tasks = [asyncio.create_task(
            self._collect_chat(req, pipeline, request_id, timer,
                               prepared=self._prepare_choice(
                                   req, pipeline, request_id, i, deadline)))
            for i in range(n)]
        try:
            results = await asyncio.gather(*tasks)
        except BaseException:
            # one choice failed: stop the surviving generations instead of
            # letting them decode to max_tokens for a response nobody gets
            for t in tasks:
                t.cancel()
            raise
        choices = []
        usage = Usage()
        for i, (text, finish_reason, lp_entries, u) in enumerate(results):
            tool_calls: Optional[List[dict]] = None
            if req.tools:
                # tool-call extraction on the aggregated message (parity:
                # ToolCallingMatcher in the reference aggregator,
                # lib/llm/src/preprocessor/tools.rs)
                from dynamo_tpu.preprocessor.tools import parse_tool_calls
                calls = parse_tool_calls(text, req.tool_choice or "auto")
                if calls:
                    tool_calls = calls
            choices.append(ChatChoice(
                index=i,
                message=ChatMessage(
                    role="assistant",
                    content=None if tool_calls else text,
                    tool_calls=tool_calls),
                finish_reason=("tool_calls" if tool_calls
                               else finish_reason or "stop"),
                logprobs=(ChoiceLogprobs(content=lp_entries)
                          if lp_entries else None)))
            _merge_choice_usage(usage, u, i)
        usage.total_tokens = usage.prompt_tokens + usage.completion_tokens
        body = ChatCompletionResponse(
            id=request_id, created=now_unix(), model=req.model,
            choices=choices, usage=usage)
        timer.done("200", usage.prompt_tokens)
        return web.json_response(body.model_dump(exclude_none=True))

    # fields the /v1/responses bridge does not implement: their presence
    # gets a 501 instead of silently changed semantics (parity:
    # validate_response_unsupported_fields, lib/llm/src/protocols/openai/
    # validate.rs)
    _RESPONSES_UNSUPPORTED = (
        "previous_response_id", "tools", "tool_choice", "reasoning",
        "store", "truncation", "include", "parallel_tool_calls",
        "background")

    async def handle_responses(self, request: web.Request) -> web.Response:
        """OpenAI Responses API, bridged through chat completions (parity:
        ``handler_responses``, ``lib/llm/src/http/service/openai.rs:583`` —
        text-only input, converted to a one-user-message chat request,
        aggregated, and shaped back into a Response object)."""
        try:
            raw = await request.json()
        except (json.JSONDecodeError, UnicodeDecodeError) as e:
            return _error(400, f"invalid request: {e}")
        if not isinstance(raw, dict):
            return _error(400, "invalid request: expected an object")
        bad = [k for k in self._RESPONSES_UNSUPPORTED
               if raw.get(k) not in (None, [], {}, False)]
        if bad:
            return _error(501, f"unsupported field(s): {', '.join(bad)}",
                          "not_implemented")
        if raw.get("stream"):
            return _error(501, "streaming responses are not implemented",
                          "not_implemented")
        if not isinstance(raw.get("input"), str):
            return _error(501, "only text input is supported",
                          "not_implemented")
        model = raw.get("model") or ""
        pipeline = self.manager.get(model)
        if pipeline is None:
            return _error(404, f"model {model!r} not found",
                          "model_not_found")
        messages = []
        if isinstance(raw.get("instructions"), str) and raw["instructions"]:
            # Responses API system prompt -> chat system message
            messages.append({"role": "system",
                             "content": raw["instructions"]})
        messages.append({"role": "user", "content": raw["input"]})
        # Responses API structured outputs: text.format carries the schema
        # INLINE ({"type": "json_schema", "schema": ..., "name": ...});
        # map to the chat response_format shape the engine understands
        response_format = None
        text_cfg = raw.get("text")
        if text_cfg not in (None, {}):
            if not isinstance(text_cfg, dict):
                return _error(400, "text must be an object")
            unknown = set(text_cfg) - {"format"}
            if unknown:
                return _error(
                    501, f"unsupported text field(s): {sorted(unknown)}",
                    "not_implemented")
            fmt = text_cfg.get("format") or {}
            if not isinstance(fmt, dict):
                return _error(400, "text.format must be an object")
            kind = fmt.get("type")
            if kind in (None, "text"):
                pass
            elif kind == "json_object":
                response_format = {"type": "json_object"}
            elif kind == "json_schema":
                response_format = {
                    "type": "json_schema",
                    "json_schema": {"name": fmt.get("name", "schema"),
                                    "schema": fmt.get("schema")}}
            else:
                return _error(400,
                              f"unsupported text.format type {kind!r}")
        try:
            chat = ChatCompletionRequest(
                model=model,
                messages=messages,
                temperature=raw.get("temperature"),
                top_p=raw.get("top_p"),
                max_tokens=raw.get("max_output_tokens"),
                response_format=response_format,
            )
        except ValidationError as e:
            return _error(400, f"invalid request: {e}")
        try:
            deadline = self._resolve_deadline(request)
        except ValueError as e:
            return _error(400, str(e))
        shed = self._shed_or_admit(model, "responses")
        if shed is not None:
            return shed
        request_id = new_request_id("resp")
        timer = RequestTimer(self.metrics, model, "responses")
        root = self.tracer.start_trace("http_request", attrs={
            "request_id": request_id, "model": model,
            "endpoint": "responses"})
        try:
            text, _finish, _lps, usage = await self._collect_chat(
                chat, pipeline, request_id, timer, deadline=deadline)
        except ValueError as e:  # same mapping as handle_chat
            timer.done("400")
            root.set_error(str(e))
            return self._stamp_rid(_error(400, str(e)), request_id)
        except DeadlineExceededError as e:
            timer.done("504")
            root.set_error(str(e))
            return self._stamp_rid(_error(504, str(e), "deadline_exceeded"),
                                   request_id)
        except ConnectionError as e:
            timer.done("503")
            root.set_error(str(e))
            return self._stamp_rid(
                _error(503, str(e), "service_unavailable"), request_id)
        except Exception as e:  # noqa: BLE001 — surface as API error
            timer.done("500")
            root.set_error(str(e))
            logger.exception("responses request %s failed", request_id)
            return self._stamp_rid(_error(500, str(e), "internal_error"),
                                   request_id)
        finally:
            self._release(model)
            root.finish()
        timer.done("200", usage.prompt_tokens)
        return self._stamp_rid(web.json_response({
            "id": request_id,
            "object": "response",
            "created_at": now_unix(),
            "model": model,
            "status": "completed",
            "output": [{
                "type": "message",
                "id": new_request_id("msg"),
                "role": "assistant",
                "status": "completed",
                "content": [{"type": "output_text", "text": text,
                             "annotations": []}],
            }],
            "usage": {"input_tokens": usage.prompt_tokens,
                      "output_tokens": usage.completion_tokens,
                      "total_tokens": usage.total_tokens,
                      # Responses-API prompt-caching surface
                      "input_tokens_details": {
                          "cached_tokens": (usage.prompt_tokens_details
                                            or {}).get("cached_tokens", 0)}},
        }), request_id)

    async def handle_completions(self, request: web.Request) -> web.StreamResponse:
        try:
            req = CompletionRequest.model_validate(await request.json())
        except (ValidationError, json.JSONDecodeError, UnicodeDecodeError) as e:
            return _error(400, f"invalid request: {e}")
        pipeline = self.manager.get(req.model)
        if pipeline is None:
            return _error(404, f"model {req.model!r} not found", "model_not_found")
        if not 1 <= req.n <= MAX_CHOICES:
            return _error(400, f"n must be between 1 and {MAX_CHOICES}")
        n = req.n
        if req.stream and n > 1:
            return _error(501, "streaming with n > 1 is not implemented "
                          "for legacy completions", "not_implemented")
        try:
            deadline = self._resolve_deadline(request, req.nvext)
        except ValueError as e:
            return _error(400, str(e))
        shed = self._shed_or_admit(req.model, "completions")
        if shed is not None:
            return shed
        request_id = new_request_id("cmpl")
        timer = RequestTimer(self.metrics, req.model, "completions")
        root = self.tracer.start_trace("http_request", attrs={
            "request_id": request_id, "model": req.model,
            "endpoint": "completions"})
        try:
            # echo: return the prompt (and, with logprobs, per-prompt-token
            # logprobs — the lm-eval loglikelihood surface) ahead of any
            # generated text. Scoring is a one-shot dense forward
            # (engine.score); max_tokens=0 makes the request pure scoring.
            # Inside the try so every early exit closes the request timer
            # and unexpected failures map like any other handler error.
            echo_text, echo_entries, echo_ids = "", None, None
            if req.echo:
                if req.stream:
                    timer.done("501")
                    return _error(501, "echo with streaming is not "
                                  "implemented", "not_implemented")
                p = req.prompt
                if (isinstance(p, list) and p
                        and isinstance(p[0], (str, list))):
                    if len(p) > 1:
                        timer.done("501")
                        return _error(501, "echo with multiple prompts is "
                                      "not implemented", "not_implemented")
                    p = p[0]
                    # the generation half must see the SAME unwrapped
                    # prompt (preprocess rejects list prompts)
                    req = req.model_copy(update={"prompt": p})
                tok = pipeline.preprocessor.tokenizer
                echo_ids = list(p) if isinstance(p, list) else tok.encode(p)
                if not echo_ids:
                    raise ValueError("echo needs a non-empty prompt")
                ds = tok.decode_stream(skip_special_tokens=False)
                pieces = [ds.step(int(t)) for t in echo_ids]
                echo_text = "".join(pieces)
                if req.logprobs is not None:
                    try:
                        lps, tids, tlps = await pipeline.score_prompt(
                            echo_ids)
                    except NotImplementedError as e:
                        timer.done("501")
                        return _error(501, str(e), "not_implemented")
                    echo_entries = []
                    # alternatives per position: up to min(requested N,
                    # the engine's num_top_logprobs) — the same cap the
                    # generation path advertises via the model card
                    n_top = min(req.logprobs, tids.shape[1])
                    for j, piece in enumerate(pieces):
                        e = {"token": piece,
                             "logprob": None if j == 0 else float(lps[j]),
                             "top_logprobs": []}
                        if j > 0 and n_top > 0:
                            e["top_logprobs"] = [
                                {"token": tok.decode(
                                    [int(tids[j, k])],
                                    skip_special_tokens=False),
                                 "logprob": float(tlps[j, k])}
                                for k in range(n_top)]
                        echo_entries.append(e)
            if req.stream:
                return await self._stream_completion(request, req, pipeline,
                                                     request_id, timer,
                                                     deadline)

            async def one_choice(i: int):
                rid, seed = self._choice_identity(request_id, req.seed, i)
                req_i = (req if i == 0
                         else req.model_copy(update={"seed": seed}))
                text_parts: List[str] = []
                lp_entries: List[dict] = []
                finish = None
                u = Usage()
                gen = pipeline.generate_completion(req_i, rid,
                                                   deadline_unix=deadline)
                try:
                    async for out in gen:
                        if out.error:
                            raise RuntimeError(out.error)
                        if out.text:
                            text_parts.append(out.text)
                            timer.on_token(len(out.token_ids) or 1)
                        if out.logprobs_content:
                            lp_entries.extend(out.logprobs_content)
                        if out.finish_reason is not None:
                            finish = out.finish_reason.to_openai()
                            u = Usage(
                                prompt_tokens=out.prompt_tokens or 0,
                                completion_tokens=out.completion_tokens or 0,
                                total_tokens=(out.prompt_tokens or 0)
                                + (out.completion_tokens or 0),
                                prompt_tokens_details=(
                                    {"cached_tokens": out.cached_tokens}
                                    if out.cached_tokens is not None
                                    else None))
                finally:
                    await gen.aclose()
                return "".join(text_parts), finish, lp_entries, u

            if req.echo and req.max_tokens == 0:
                # pure scoring: no generation at all. Only an EXPLICIT 0 —
                # a JSON null means "the default", like the non-echo path
                u0 = Usage(prompt_tokens=len(echo_ids),
                           total_tokens=len(echo_ids))
                results = [("", "length", [], u0) for _ in range(n)]
            else:
                tasks = [asyncio.create_task(one_choice(i))
                         for i in range(n)]
                try:
                    results = await asyncio.gather(*tasks)
                except BaseException:
                    for t in tasks:
                        t.cancel()
                    raise
            usage = Usage()
            choices = []
            for i, (text, finish, lp_entries, u) in enumerate(results):
                if req.echo:
                    text = echo_text + text
                    if echo_entries is not None:
                        lp_entries = echo_entries + lp_entries
                choices.append(CompletionChoice(
                    index=i, text=text,
                    finish_reason=finish or "stop",
                    logprobs=(_legacy_logprobs(lp_entries)[0]
                              if lp_entries else None)))
                _merge_choice_usage(usage, u, i)
            usage.total_tokens = (usage.prompt_tokens
                                  + usage.completion_tokens)
            body = CompletionResponse(
                id=request_id, created=now_unix(), model=req.model,
                choices=choices, usage=usage)
            timer.done("200", usage.prompt_tokens)
            return self._stamp_rid(
                web.json_response(body.model_dump(exclude_none=True)),
                request_id)
        except ValueError as e:
            timer.done("400")
            root.set_error(str(e))
            return self._stamp_rid(_error(400, str(e)), request_id)
        except DeadlineExceededError as e:
            timer.done("504")
            root.set_error(str(e))
            return self._stamp_rid(_error(504, str(e), "deadline_exceeded"),
                                   request_id)
        except ConnectionResetError:
            timer.done("499")
            root.set_error("client disconnected")
            raise
        except ConnectionError as e:
            timer.done("503")
            root.set_error(str(e))
            return self._stamp_rid(
                _error(503, str(e), "service_unavailable"), request_id)
        except asyncio.CancelledError:
            timer.done("499")
            root.set_error("cancelled")
            raise
        except Exception as e:
            logger.exception("completions handler error")
            timer.done("500")
            root.set_error(str(e))
            return self._stamp_rid(_error(500, str(e), "internal_error"),
                                   request_id)
        finally:
            self._release(req.model)
            root.finish()

    async def _stream_completion(self, http_req: web.Request,
                                 req: CompletionRequest, pipeline,
                                 request_id: str, timer: RequestTimer,
                                 deadline: Optional[float] = None
                                 ) -> web.StreamResponse:
        resp = web.StreamResponse(headers={
            "Content-Type": "text/event-stream",
            "Cache-Control": "no-cache",
            "X-Request-Id": request_id})
        await resp.prepare(http_req)
        status = "200"
        created = now_unix()
        gen = pipeline.generate_completion(req, request_id,
                                           deadline_unix=deadline)
        lp_offset = 0
        try:
            async for out in gen:
                if out.error:
                    raise RuntimeError(out.error)
                # logprobs_content gates emission too: a frame may carry
                # token logprobs whose text is still held by the decoder
                if out.text or out.logprobs_content or (
                        out.finish_reason is not None):
                    timer.on_token(len(out.token_ids) or (1 if out.text else 0))
                    lp_obj = None
                    if out.logprobs_content:
                        lp_obj, lp_offset = _legacy_logprobs(
                            out.logprobs_content, lp_offset)
                    chunk = CompletionResponse(
                        id=request_id, created=created, model=req.model,
                        choices=[CompletionChoice(
                            text=out.text or "",
                            finish_reason=(out.finish_reason.to_openai()
                                           if out.finish_reason else None),
                            logprobs=lp_obj)])
                    await resp.write(sse.encode_data(
                        chunk.model_dump(exclude_none=True)))
            await resp.write(sse.encode_done())
        except (ConnectionResetError, asyncio.CancelledError):
            status = "499"
            raise
        except DeadlineExceededError as e:
            status = "504"
            await _sse_error(resp, e, "deadline_exceeded")
        except Exception as e:
            logger.exception("completion stream error for %s", request_id)
            status = "500"
            await _sse_error(resp, e, "internal_error")
        finally:
            await gen.aclose()
            timer.done(status)
        await resp.write_eof()
        return resp


__all__ = ["HttpService"]
