"""OpenAI-compatible HTTP frontend service."""

from dynamo_tpu.http.service import HttpService

__all__ = ["HttpService"]
