"""KVBM: the multi-tier KV block manager.

Capability parity with the reference's KVBM (``lib/llm/src/block_manager/``
~12k LoC: CacheLevel G1 gpu / G2 host / G3 disk / G4 remote, offload +
onboard managers, CUDA/NIXL transfer strategies), re-designed around this
framework's content-addressed blocks:

- **G1 (HBM)** is the engine's paged device cache + ``PageAllocator`` LRU.
- **G2 (host RAM)** and **G3 (disk)** are byte-budgeted LRU pools of block
  payloads keyed by chained block hash (``tiers.py``).
- **Offload** is event-driven: the allocator's eviction hook fires before a
  page is reused; the manager snapshots the block device->host (the jax
  array is an immutable snapshot, so this is race-free against in-flight
  steps). Host-pool overflow demotes G2 -> G3.
- **Onboard** is pipelined lookahead (``prefetch.py``, the packing-prefetch
  scheduler): the first prefill chunk's blocks inject synchronously so
  admission's prefix match sees them, and the rest stream in pinned ahead
  of the chunked-prefill cursor — adopted mid-prefill by the engine
  scheduler instead of recomputed. ``DYN_KV_PREFETCH_DEPTH=0`` restores
  the bounded synchronous onboard.
- **G4 (remote)** is the disagg block-transfer plane itself
  (``worker/disagg.py``): remote workers' caches are reachable by the same
  hashes over the RPC plane.

Replaces ``block_copy.cu`` + CUDA-stream transfer contexts with jax
device_get/device_put gathers (XLA handles batching/overlap).
"""

from dynamo_tpu.kvbm.manager import TieredEngine, TieredKvConfig
from dynamo_tpu.kvbm.prefetch import PrefetchScheduler, prefetch_depth_bytes
from dynamo_tpu.kvbm.tiers import DiskTier, HostTier

__all__ = ["TieredEngine", "TieredKvConfig", "HostTier", "DiskTier",
           "PrefetchScheduler", "prefetch_depth_bytes"]
