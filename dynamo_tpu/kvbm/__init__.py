"""KVBM: the multi-tier KV block manager.

Capability parity with the reference's KVBM (``lib/llm/src/block_manager/``
~12k LoC: CacheLevel G1 gpu / G2 host / G3 disk / G4 remote, offload +
onboard managers, CUDA/NIXL transfer strategies), re-designed around this
framework's content-addressed blocks:

- **G1 (HBM)** is the engine's paged device cache + ``PageAllocator`` LRU.
- **G2 (host RAM)** and **G3 (disk)** are byte-budgeted LRU pools of block
  payloads keyed by chained block hash (``tiers.py``).
- **Offload** is event-driven: the allocator's eviction hook fires before a
  page is reused; the manager snapshots the block device->host (the jax
  array is an immutable snapshot, so this is race-free against in-flight
  steps). Host-pool overflow demotes G2 -> G3.
- **Onboard** happens at request admission: prompt blocks missing from HBM
  but resident in G2/G3 are injected back through the same content-addressed
  path disaggregation uses (``engine/transfer.py``), after which the normal
  prefix-match admission revives them — no scheduler changes.
- **G4 (remote)** is the disagg block-transfer plane itself
  (``worker/disagg.py``): remote workers' caches are reachable by the same
  hashes over the RPC plane.

Replaces ``block_copy.cu`` + CUDA-stream transfer contexts with jax
device_get/device_put gathers (XLA handles batching/overlap).
"""

from dynamo_tpu.kvbm.manager import TieredEngine, TieredKvConfig
from dynamo_tpu.kvbm.tiers import DiskTier, HostTier

__all__ = ["TieredEngine", "TieredKvConfig", "HostTier", "DiskTier"]
