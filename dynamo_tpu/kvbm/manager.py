"""Offload/onboard orchestration across the KV tiers.

Parity in role: reference ``OffloadManager`` (``block_manager/offload.rs`` —
G1->G2->G3 offload, onboarding with batched transfers). Here transfers are
jax gathers (device->host) and the content-addressed inject path
(``engine/transfer.py``) — no CUDA streams/NIXL agents to manage.

``TieredEngine`` wraps any ``JaxEngine``:
- installs the allocator eviction hook: HBM-evicted blocks snapshot into G2
  (host RAM), G2 overflow demotes to G3 (disk);
- on ``generate``, prompt blocks missing from HBM but held by G2/G3 are
  injected back into the device cache, then normal admission prefix-matches
  them. Onboarding pulls G3 hits back through G2 (promotion on use).
"""

from __future__ import annotations

import logging
from dataclasses import dataclass
from typing import AsyncIterator, List, Optional

from dynamo_tpu.engine.jax_engine import JaxEngine
from dynamo_tpu.engine.base import EngineBase
from dynamo_tpu.engine.transfer import (
    BlockPayload,
    _gather_pages,
    inject_blocks,
)
from dynamo_tpu.protocols.common import LLMEngineOutput, PreprocessedRequest
from dynamo_tpu.kvbm.tiers import DiskTier, HostTier
from dynamo_tpu.tokens import compute_block_hash_for_seq

logger = logging.getLogger(__name__)


@dataclass
class TieredKvConfig:
    host_budget_bytes: int = 1 << 30          # G2: 1 GiB default
    disk_budget_bytes: int = 0                # G3: 0 = disabled
    disk_path: str = "/tmp/dynamo_tpu_kvbm"
    # cap on blocks onboarded per request (bound admission latency)
    max_onboard_blocks: int = 256


class TieredEngine(EngineBase):
    """EngineBase wrapper adding G2/G3 offload tiers to a JaxEngine."""

    def __init__(self, engine: JaxEngine,
                 config: Optional[TieredKvConfig] = None):
        self.engine = engine
        self.cfg = config or TieredKvConfig()
        self.host = HostTier(self.cfg.host_budget_bytes)
        self.disk = (DiskTier(self.cfg.disk_path, self.cfg.disk_budget_bytes)
                     if self.cfg.disk_budget_bytes > 0 else None)
        self.offloaded = 0
        self.onboarded = 0
        engine.allocator.on_evict = self._on_evict

    # -- offload (G1 -> G2 -> G3) -----------------------------------------

    def _on_evict(self, evicted: List[tuple]) -> None:
        """Allocator eviction hook: snapshot blocks to the host tier.

        Runs synchronously before the pages are reused; the gather reads the
        current immutable device array snapshot.
        """
        try:
            data = _gather_pages(self.engine, [p for _h, p, _i in evicted])
        except Exception:
            logger.exception("kvbm offload gather failed; blocks dropped")
            return
        for i, (h, _page, info) in enumerate(evicted):
            blk = BlockPayload(block_hash=h, local_hash=info.local_hash,
                               parent_hash=info.parent_hash,
                               data=data[:, i].copy())
            self.offloaded += 1
            for demoted in self.host.put(blk):
                if self.disk is not None:
                    self.disk.put(demoted)

    # -- onboard (G2/G3 -> G1) --------------------------------------------

    def _lookup(self, block_hash: int) -> Optional[BlockPayload]:
        blk = self.host.get(block_hash)
        if blk is None and self.disk is not None:
            blk = self.disk.get(block_hash)
            if blk is not None:
                for demoted in self.host.put(blk):  # promote on use
                    self.disk.put(demoted)
        return blk

    def _onboard_for(self, token_ids: List[int]) -> int:
        """Inject tier-resident prompt blocks missing from HBM."""
        page_size = self.engine.allocator.page_size
        hashes = compute_block_hash_for_seq(token_ids, page_size)
        resident = self.engine.allocator._by_hash
        needed: List[BlockPayload] = []
        for h in hashes[:self.cfg.max_onboard_blocks]:
            if h in resident:
                continue
            blk = self._lookup(h)
            if blk is None:
                break  # chain broken: further blocks can't be used
            needed.append(blk)
        if not needed:
            return 0
        n = inject_blocks(self.engine, needed)
        self.onboarded += n
        return n

    # -- EngineBase --------------------------------------------------------

    async def generate(self, request: PreprocessedRequest,
                       ctx=None) -> AsyncIterator[LLMEngineOutput]:
        if request.token_ids:
            # serialized with the step loop: onboarding reassigns
            # engine.pages, which is donated through every step
            await self.engine.run_exclusive(
                self._onboard_for, request.token_ids)
        async for out in self.engine.generate(request, ctx):
            yield out

    async def start(self) -> None:
        await self.engine.start()

    async def stop(self) -> None:
        await self.engine.stop()

    def stats(self):
        return self.engine.stats()


__all__ = ["TieredEngine", "TieredKvConfig"]
