"""Offload/onboard orchestration across the KV tiers.

Parity in role: reference ``OffloadManager`` (``block_manager/offload.rs`` —
G1->G2->G3 offload with bounded queues off the hot path, onboarding with
batched transfers). Here transfers are jax gathers (device->host) and the
content-addressed inject path (``engine/transfer.py``) — no CUDA
streams/NIXL agents to manage.

``TieredEngine`` wraps any ``JaxEngine``:
- installs the allocator eviction hook: HBM-evicted blocks are snapshotted
  ON DEVICE (an async jitted gather — no host sync, runs between steps) and
  handed to a background spill thread through a BOUNDED queue; the thread
  does the device->host copy and the G2/G3 tier writes (disk IO never runs
  on the eviction path, so an eviction storm cannot stall a decode step —
  reference analog: ``offload.rs:80-99``'s bounded offload queues).
  When the queue is full the oldest pending spill is dropped and counted:
  the tiers are best-effort caches, blocking the engine is worse than
  losing a re-computable block.
- on ``generate``, prompt blocks missing from HBM but held by G2/G3 are
  injected back into the device cache, then normal admission prefix-matches
  them. Onboarding pulls G3 hits back through G2 (promotion on use).
"""

from __future__ import annotations

import logging
import queue
import threading
from dataclasses import dataclass
from typing import AsyncIterator, Dict, List, Optional

import numpy as np

from dynamo_tpu.engine.jax_engine import JaxEngine
from dynamo_tpu.engine.base import EngineBase
from dynamo_tpu.engine.transfer import (
    BlockPayload,
    inject_blocks,
)
from dynamo_tpu.protocols.common import LLMEngineOutput, PreprocessedRequest
from dynamo_tpu.kvbm.tiers import DiskTier, HostTier
from dynamo_tpu.tokens import compute_block_hash_for_seq

logger = logging.getLogger(__name__)


@dataclass
class TieredKvConfig:
    host_budget_bytes: int = 1 << 30          # G2: 1 GiB default
    disk_budget_bytes: int = 0                # G3: 0 = disabled
    disk_path: str = "/tmp/dynamo_tpu_kvbm"
    # cap on blocks onboarded SYNCHRONOUSLY per request. With the prefetch
    # scheduler on (the default) only the first prefill chunk's blocks
    # onboard synchronously (min of that and this cap) — the rest stream
    # in ahead of the chunked-prefill cursor; with lookahead disabled
    # (depth 0) this is the old hard cap on the whole onboard.
    max_onboard_blocks: int = 256
    # bounded background spill queue (eviction batches in flight)
    max_pending_spills: int = 8
    # packing-prefetch lookahead depth in bytes; None = resolve
    # DYN_KV_PREFETCH_DEPTH / RuntimeConfig.kv_prefetch_depth, 0 disables
    prefetch_depth_bytes: Optional[int] = None


class TieredEngine(EngineBase):
    """EngineBase wrapper adding G2/G3 offload tiers to a JaxEngine."""

    def __init__(self, engine: JaxEngine,
                 config: Optional[TieredKvConfig] = None):
        from dynamo_tpu.kvbm.prefetch import (
            PrefetchScheduler, prefetch_depth_bytes)

        self.engine = engine
        self.cfg = config or TieredKvConfig()
        self.host = HostTier(self.cfg.host_budget_bytes)
        self.disk = (DiskTier(self.cfg.disk_path, self.cfg.disk_budget_bytes)
                     if self.cfg.disk_budget_bytes > 0 else None)
        self.offloaded = 0
        self.onboarded = 0
        self.dropped_spills = 0
        # RLock: _lookup acquires it internally and is also called from
        # sections that already hold it (collect_tiered_blocks)
        self._tier_lock = threading.RLock()
        self._pending_lock = threading.Lock()
        depth = (prefetch_depth_bytes()
                 if self.cfg.prefetch_depth_bytes is None
                 else int(self.cfg.prefetch_depth_bytes))
        # the lookahead promotion scheduler (kvbm/prefetch.py); None =
        # legacy synchronous onboarding
        self.prefetch = (PrefetchScheduler(self, depth)
                         if depth > 0 else None)
        self._pending_hashes: set = set()
        self._spills: "queue.Queue" = queue.Queue(
            maxsize=self.cfg.max_pending_spills)
        self._spill_thread: Optional[threading.Thread] = None
        self._peer_client = None          # G4 (enable_peer_fetch)
        self._self_instance_id = -1
        self._global_index = None         # fleet prefix index (holder order)
        self.peer_onboarded = 0
        # admission-path onboard accounting: blocks/bytes served by a peer
        # pull vs left for local recompute (the fleet-KV-reuse A/B signal)
        self.onboard_peer_blocks = 0
        self.onboard_peer_bytes = 0
        self.onboard_recompute_blocks = 0
        self.onboard_recompute_bytes = 0
        engine.allocator.on_evict = self._on_evict

    # -- offload (G1 -> G2 -> G3) -----------------------------------------

    def _on_evict(self, evicted: List[tuple]) -> None:
        """Allocator eviction hook — must return fast.

        Runs between engine steps (evictions happen in the scheduler, which
        is serialized with the step loop), so the device gather reads a
        consistent cache. Only the gather DISPATCH happens here; the
        device->host copy and tier writes run on the spill thread.
        """
        try:
            # dispatch_gather_pages broadcasts on a multi-host mesh (every
            # rank joins the gather on the sharded cache) and returns a
            # replicated handle the spill thread can read locally
            data_dev = self.engine.dispatch_gather_pages(
                [p for _h, p, _i in evicted])
        except Exception:
            logger.exception("kvbm offload gather failed; blocks dropped")
            return
        metas = [(h, info.local_hash, info.parent_hash)
                 for h, _page, info in evicted]
        with self._pending_lock:
            self._pending_hashes.update(h for h, _l, _p in metas)
        item = (metas, data_dev)
        try:
            self._spills.put_nowait(item)
        except queue.Full:
            try:  # drop the OLDEST pending batch, keep the freshest
                old_metas, _old = self._spills.get_nowait()
                with self._pending_lock:
                    self._pending_hashes.difference_update(
                        h for h, _l, _p in old_metas)
                self._spills.task_done()
                self.dropped_spills += 1
            except queue.Empty:
                pass
            try:
                self._spills.put_nowait(item)
            except queue.Full:
                self.dropped_spills += 1
                return
        if self._spill_thread is None or not self._spill_thread.is_alive():
            self._spill_thread = threading.Thread(
                target=self._spill_loop, daemon=True, name="kvbm-spill")
            self._spill_thread.start()

    def _spill_loop(self) -> None:
        # daemon thread, lives for the engine's lifetime: retiring on idle
        # races the producer's is_alive() check and can strand a batch
        while True:
            metas, data_dev = self._spills.get()
            try:
                host = np.asarray(data_dev)  # the device->host copy
                to_disk: List[BlockPayload] = []
                with self._tier_lock:
                    for i, (h, local, parent) in enumerate(metas):
                        blk = BlockPayload(block_hash=h, local_hash=local,
                                           parent_hash=parent,
                                           data=host[:, i].copy())
                        self.offloaded += 1
                        to_disk.extend(self.host.put(blk))
                if self.disk is not None:
                    # G2->G3 demotion writes OUTSIDE the tier lock: a slow
                    # disk must only stall this spill thread, never an
                    # onboard/prefetch probe waiting on the lock
                    for demoted in to_disk:
                        self.disk.put(demoted)
            except Exception:
                logger.exception("kvbm spill batch failed; blocks dropped")
            finally:
                with self._pending_lock:
                    self._pending_hashes.difference_update(
                        h for h, _l, _p in metas)
                self._spills.task_done()

    def flush_spills(self, timeout: float = 10.0) -> None:
        """Block until every pending spill landed in a tier."""
        import time
        deadline = time.monotonic() + timeout
        while (self._spills.unfinished_tasks
               and time.monotonic() < deadline):
            time.sleep(0.01)

    # -- onboard (G2/G3 -> G1) --------------------------------------------

    def _lookup(self, block_hash: int) -> Optional[BlockPayload]:
        """One tier lookup with disk->host promotion on use. Acquires the
        tier lock internally (RLock — callers may already hold it); when
        called WITHOUT it held (the prefetch worker thread), the disk file
        read and the G2->G3 demotion write-back run outside the host-tier
        lock, so slow disk IO never serializes other tier operations."""
        with self._tier_lock:
            blk = self.host.get(block_hash)
        if blk is not None or self.disk is None:
            return blk
        blk = self.disk.get(block_hash)  # file IO under the disk's own lock
        if blk is None:
            return None
        with self._tier_lock:
            demoted = self.host.put(blk)  # promote on use
        for d in demoted:
            self.disk.put(d)
        return blk

    def _onboard_for(self, token_ids: List[int],
                     cap: Optional[int] = None,
                     host_only: bool = False,
                     hashes: Optional[List[int]] = None) -> int:
        """Inject tier-resident prompt blocks missing from HBM — the
        bounded SYNCHRONOUS path: the prefetch scheduler's first-chunk
        fast path (``cap`` = the first prefill chunk's blocks), or the
        whole legacy onboard when lookahead is disabled.

        ``host_only`` keeps this path off the disk tier (and the spill
        flush) entirely: it runs inside the engine's exclusive window,
        and a wedged disk must never stall the step loop — disk-resident
        blocks are promoted asynchronously by the prefetcher (or
        recomputed). ``hashes`` lets the caller pass the already-computed
        chain so a 100k-token prompt isn't re-hashed inside the window."""
        page_size = self.engine.allocator.page_size
        if hashes is None:
            hashes = compute_block_hash_for_seq(token_ids, page_size)
        cap = self.cfg.max_onboard_blocks if cap is None else int(cap)
        # onboarding must observe completed offloads — but only wait when a
        # NEEDED block is actually still in the spill queue; flushing every
        # pending batch here would re-serialize slow tier writes onto the
        # step loop at every admission. NEVER on the host_only fast path:
        # flush_spills waits out the spill thread's G2->G3 disk writes,
        # and a wedged disk must not stall the exclusive window this runs
        # in — a pending block simply misses here and the async
        # prefetcher (which flushes on ITS thread) promotes it instead.
        if not host_only:
            with self._pending_lock:
                overlap = bool(self._pending_hashes.intersection(
                    h for h in hashes[:cap]))
            if overlap:
                self.flush_spills()
        resident = self.engine.allocator._by_hash
        needed: List[BlockPayload] = []
        with self._tier_lock:
            for h in hashes[:cap]:
                if h in resident:
                    continue
                blk = (self.host.get(h) if host_only
                       else self._lookup(h))
                if blk is None:
                    break  # chain broken: further blocks can't be used
                needed.append(blk)
        if not needed:
            return 0
        n = inject_blocks(self.engine, needed)
        self.onboarded += n
        return n

    # -- G4: cross-worker peer tier ---------------------------------------

    def enable_peer_fetch(self, kv_client, self_instance_id: int) -> None:
        """Turn on the G4 remote tier: on a local tier miss, fetch the
        missing chain from a peer worker's ``kv_export`` endpoint (content
        addressing makes any peer's copy byte-identical). Reference:
        ``CacheLevel::G4`` + distributed leader/worker,
        ``block_manager.rs:67-81``, ``block_manager/distributed/``."""
        self._peer_client = kv_client
        self._self_instance_id = self_instance_id
        self.peer_onboarded = 0

    def enable_global_index(self, reader) -> None:
        """Attach a fleet prefix-index mirror
        (``kv_router.global_index.GlobalPrefixIndexReader``): peer pulls
        walk KNOWN HOLDERS in overlap order instead of every live
        instance blindly."""
        self._global_index = reader

    def _peer_order(self, hashes: List[int]) -> List[int]:
        """Pull order over live peers: global-index holders first (longest
        overlap first), then the unindexed rest as a blind fallback."""
        live = [iid for iid in self._peer_client.instance_ids()
                if iid != self._self_instance_id]
        if self._global_index is None:
            return live
        ranked = [iid for iid in self._global_index.holder_order(
                      hashes, exclude=(self._self_instance_id,))
                  if iid in set(live)]
        seen = set(ranked)
        return ranked + [iid for iid in live if iid not in seen]

    async def _onboard_from_peers(self, token_ids: List[int]) -> int:
        """Fetch the first-missing chain suffix from peer workers —
        holders first — with the export-lease/resume ladder: each pull
        asks the exporter to pin the served blocks under a TTL'd lease
        (acked once committed), a broken stream keeps its landed blocks
        and RESUMES (same peer once, then the next holder) re-pulling
        only what is still missing, and whatever no peer can serve is
        left for local recompute — with both halves (peer-onboarded vs
        recomputed blocks AND bytes) recorded on the ``kv_transfer`` span
        and the ``dynamo_worker_kv_onboard_*`` counters."""
        import time as _time

        from dynamo_tpu.engine.transfer import (
            FRAME_WIRE_VERSION, InjectPipeline, kv_shard_payload)
        from dynamo_tpu.kvbm.prefetch import _block_bytes
        from dynamo_tpu.utils.tracing import get_tracer
        from dynamo_tpu.worker.disagg import get_kv_bandwidth_book
        from dynamo_tpu.worker.metrics import count_metric

        page_size = self.engine.allocator.page_size
        hashes = compute_block_hash_for_seq(token_ids, page_size)
        hashes = hashes[:self.cfg.max_onboard_blocks]
        resident = self.engine.allocator._by_hash
        with self._tier_lock:
            missing_from = next(
                (i for i, h in enumerate(hashes)
                 if h not in resident and self.host.get(h) is None
                 and (self.disk is None or self.disk.get(h) is None)),
                None)
        if missing_from is None:
            return 0
        want = hashes[missing_from:]
        block_bytes = _block_bytes(self.engine)
        span = get_tracer().start_span(
            "kv_transfer", attrs={"path": "admission_onboard",
                                  "blocks": len(want)})
        injected = 0
        pulled_bytes = 0
        try:
            for iid in self._peer_order(hashes):
                # resume across peers: blocks a previous (partially
                # failed) peer fetch already committed are content-
                # addressed resident — the next peer only serves what is
                # still missing. One same-peer resume first (the PR 6
                # ladder): a transient stream break re-pulls the tail
                # before the walk moves on.
                for attempt in range(2):
                    want = [h for h in want if h not in resident]
                    if not want:
                        break
                    if attempt:
                        span.add_event("pull_resumed", plane="rpc",
                                       peer=f"{iid:x}",
                                       remaining=len(want))
                        count_metric("kv_pull_resumes")
                    pipe = None
                    lease = None
                    nbytes = 0
                    t0 = _time.perf_counter()
                    try:
                        from dynamo_tpu.runtime.codec import release_buffer
                        # wire-v5 pull: shard negotiation rides the
                        # payload (tiered exporters answer merged frames;
                        # a same-layout HBM exporter streams per-shard),
                        # and want_lease pins the served blocks on the
                        # exporter until the commit ack below
                        stream = await self._peer_client.direct(
                            {"block_hashes": want,
                             "wire": FRAME_WIRE_VERSION,
                             "want_lease": 1,
                             **kv_shard_payload(self.engine)}, iid)
                        # staged pipeline: frames batch into bounded
                        # donated scatters, so a big onboard doesn't
                        # stall decode steps
                        pipe = InjectPipeline(self.engine)
                        async for frame in stream:
                            if frame.get("lease") is not None:
                                lease = int(frame["lease"])
                                span.set_attr("kv_export_lease", lease)
                                continue
                            if "_raw" not in frame:
                                continue
                            nbytes += len(frame["_raw"])
                            # pipeline recycles the pooled trailer once
                            # consumed
                            await pipe.add_frame(frame,
                                                 release=release_buffer)
                        injected += await pipe.finish()
                        dt = _time.perf_counter() - t0
                        pulled_bytes += nbytes
                        if nbytes:
                            # admission pulls ride the RPC plane: feed the
                            # same bandwidth EWMA the router prices with
                            get_kv_bandwidth_book().note("rpc", nbytes, dt)
                        break
                    except BaseException as e:  # incl. CancelledError —
                        # the pipeline's in-flight commits must be reaped
                        # either way
                        if pipe is not None:
                            # reap in-flight commits (no leaked task
                            # exceptions) and keep what landed: content-
                            # addressed blocks from a broken stream are
                            # still good prefix the resume dedups against
                            injected += await pipe.drain()
                        pulled_bytes += nbytes
                        if not isinstance(e, Exception):
                            raise  # cancellation propagates after the reap
                        logger.debug("G4 peer %x fetch failed: %s", iid, e)
                        continue
                    finally:
                        if lease is not None:
                            # commit/abandon ack either way: the exporter
                            # unpins now instead of waiting out the TTL
                            acked = await self._ack_peer_lease(iid, lease)
                            span.set_attr("lease_acked", acked)
                # no break on clean partial service: a peer that served
                # only part of the chain (the rest fell out of its tiers)
                # is not the end — the want-filter stops the walk once
                # nothing is missing, otherwise the next holder serves
                # the remainder
                want = [h for h in want if h not in resident]
                if not want:
                    break
        finally:
            # the recompute-vs-onboard split this admission decided:
            # whatever no peer could serve is prefill work
            recompute = len([h for h in want if h not in resident])
            self.peer_onboarded += injected
            self.onboard_peer_blocks += injected
            self.onboard_peer_bytes += pulled_bytes
            self.onboard_recompute_blocks += recompute
            self.onboard_recompute_bytes += recompute * block_bytes
            span.set_attr("onboarded_blocks", injected)
            span.set_attr("onboarded_bytes", pulled_bytes)
            span.set_attr("recompute_blocks", recompute)
            span.set_attr("recompute_bytes", recompute * block_bytes)
            span.finish()
            if injected:
                count_metric("kv_onboard", "peer", inc=injected)
                count_metric("kv_onboard_bytes", "peer", inc=pulled_bytes)
            if recompute:
                count_metric("kv_onboard", "recompute", inc=recompute)
                count_metric("kv_onboard_bytes", "recompute",
                             inc=recompute * block_bytes)
        return injected

    async def _ack_peer_lease(self, iid: int, lease: int) -> bool:
        try:
            stream = await self._peer_client.direct(
                {"ack_lease": int(lease)}, iid)
            async for _ in stream:
                pass
            return True
        except Exception as e:  # noqa: BLE001 — the exporter's TTL covers
            logger.debug("onboard lease %s ack to %x failed (%s); TTL "
                         "covers", lease, iid, e)
            return False

    # -- EngineBase --------------------------------------------------------

    async def generate(self, request: PreprocessedRequest,
                       ctx=None) -> AsyncIterator[LLMEngineOutput]:
        handle = None
        if request.token_ids:
            if not request.request_id:
                # the engine assigns this same fallback id later; the
                # prefetch cursor needs it NOW to track the sequence
                request.request_id = f"req-{id(request):x}"
            if self.prefetch is not None:
                # admission lookahead: the first prefill chunk's blocks
                # onboard synchronously so admission's prefix match sees
                # them; later chunks' blocks stream in pinned ahead of the
                # chunked-prefill cursor and are adopted mid-prefill
                # (Scheduler._adopt_resident) instead of recomputed
                handle = await self.prefetch.admit(request)
            else:
                # legacy path (DYN_KV_PREFETCH_DEPTH=0): serialized with
                # the step loop — onboarding reassigns engine.pages, which
                # is donated through every step
                await self.engine.run_exclusive(
                    self._onboard_for, request.token_ids)
            if self._peer_client is not None:
                try:
                    await self._onboard_from_peers(request.token_ids)
                except Exception:  # noqa: BLE001 — G4 must never fail a req
                    logger.exception("G4 peer onboard failed")
        try:
            async for out in self.engine.generate(request, ctx):
                yield out
        finally:
            if handle is not None:
                # commit or abort: release the promotion pins (the
                # sequence's own page refs — or the LRU — own them now)
                await handle.close()

    async def start(self) -> None:
        await self.engine.start()

    async def stop(self) -> None:
        await self.engine.stop()

    def stats(self):
        return self.engine.stats()

    def kvbm_stats(self) -> Dict[str, float]:
        """Tier/pool gauges for the stats plane (worker ``__stats__`` →
        frontend Prometheus; reference: block-manager pool metrics)."""
        with self._tier_lock:
            out = {
                "kvbm_offloaded_blocks": self.offloaded,
                "kvbm_onboarded_blocks": self.onboarded,
                "kvbm_dropped_spills": self.dropped_spills,
                "kvbm_host_blocks": len(self.host),
                "kvbm_host_bytes": self.host.used,
                "kvbm_pending_spills": self._spills.qsize(),
                "kvbm_peer_onboarded_blocks": self.peer_onboarded,
                "kvbm_onboard_peer_bytes": self.onboard_peer_bytes,
                "kvbm_onboard_recompute_blocks":
                    self.onboard_recompute_blocks,
                "kvbm_onboard_recompute_bytes":
                    self.onboard_recompute_bytes,
            }
            if self.disk is not None:
                out["kvbm_disk_blocks"] = len(self.disk)
                out["kvbm_disk_bytes"] = self.disk.used
                out["kvbm_disk_corrupt_dropped"] = self.disk.corrupt_dropped
        # mid-prefill prefix adoptions (the consumer half of the prefetch
        # pipeline) live on the engine scheduler
        out["kvbm_prefetch_adopted_blocks"] = \
            self.engine.scheduler.adopted_blocks
        if self.prefetch is not None:
            out.update(self.prefetch.stats())
        return out


def collect_tiered_blocks(tiered: TieredEngine,
                          hashes: List[int]) -> List[BlockPayload]:
    """HBM-resident prefix first (device gather), then continue the chain
    from the G2/G3 tiers; stop at the first total miss. Runs under
    ``run_exclusive``."""
    from dynamo_tpu.engine.transfer import export_blocks

    blocks = export_blocks(tiered.engine, hashes)
    with tiered._tier_lock:
        for h in hashes[len(blocks):]:
            blk = tiered._lookup(h)
            if blk is None:
                break
            blocks.append(blk)
    return blocks


def tiered_export_frames(tiered: TieredEngine, hashes: List[int],
                         layout: str = "layer",
                         frame_blocks: Optional[int] = None):
    """Batched Raw wire frames spanning HBM + tiers (the tier-aware
    counterpart of ``transfer.export_frames``; shared by the RPC and bulk
    planes so neither silently misses tier-resident blocks). ``layout``
    follows the same wire schema: layer-major v3 for new pullers,
    block-major v2 compat otherwise; wire-v4 checksums are stamped by the
    handlers afterward (``transfer.stamp_frame_crcs``, outside the
    exclusive window). Runs under ``run_exclusive``."""
    from dynamo_tpu.engine.transfer import kv_transfer_defaults
    from dynamo_tpu.runtime.codec import Raw

    # handlers resolve the knob outside the exclusive window and pass it
    per = (int(frame_blocks) if frame_blocks
           else kv_transfer_defaults()[0])
    blocks = collect_tiered_blocks(tiered, hashes)
    frames = []
    for i in range(0, len(blocks), per):
        chunk = blocks[i:i + per]
        meta = {"blocks": [[b.block_hash, b.local_hash, b.parent_hash]
                           for b in chunk]}
        if layout == "layer":
            data = np.ascontiguousarray(
                np.stack([b.data for b in chunk], axis=1))
            meta["block_shape"] = [data.shape[0]] + list(data.shape[2:])
            meta["layout"] = "layer"
        else:
            data = np.ascontiguousarray(
                np.stack([b.data for b in chunk], axis=0))
            meta["block_shape"] = list(data.shape[1:])
        meta["dtype"] = str(data.dtype)
        frames.append(Raw(meta, data))
    return frames


def serve_tiered_kv_export(tiered: TieredEngine):
    """RPC handler: like ``transfer.serve_kv_export`` but also serves
    blocks held only in this worker's G2/G3 tiers — the provider side of
    the G4 remote tier (peers fetch what fell out of our HBM)."""
    from dynamo_tpu.engine.transfer import (
        grant_export_lease,
        release_export_lease,
        resolve_wire,
    )

    async def handler(payload, ctx):
        payload = payload or {}
        if payload.get("ack_lease") is not None:
            # puller committed its pull: unpin the export lease now
            # instead of waiting out the TTL GC
            ok = await release_export_lease(tiered.engine,
                                            int(payload["ack_lease"]))
            yield {"acked": bool(ok)}
            return
        hashes = list(payload.get("block_hashes", []))
        if payload.get("want_lease"):
            # puller-initiated pulls (admission onboarding) have no
            # advertise step to grant a lease through: grant one here so
            # the HBM-resident slice of the chain can't be evicted out
            # from under the stream; tier-resident blocks need no pin.
            # The puller acks {"ack_lease": id} once committed; the TTL
            # GC covers a lost ack.
            lease = await grant_export_lease(tiered.engine, hashes)
            if lease is not None:
                yield {"lease": int(lease)}
        if int(payload.get("wire", 1)) >= 2:
            # tiered exports serve merged frames regardless of the shard
            # negotiation: tier-resident blocks live as unsharded host
            # bytes, so there is no per-shard slice to stream
            layout, per, crc, _shards = resolve_wire(payload, 1)
            frames = await tiered.engine.run_exclusive(
                tiered_export_frames, tiered, hashes, layout, per)
            if crc:  # outside the exclusive window
                from dynamo_tpu.engine.transfer import stamp_frame_crcs
                stamp_frame_crcs(frames)
            for f in frames:
                yield f
        else:
            blocks = await tiered.engine.run_exclusive(
                collect_tiered_blocks, tiered, hashes)
            for b in blocks:
                yield b.to_wire()

    return handler


def serve_tiered_kv_export_bulk(tiered: TieredEngine, loop):
    """Bulk-plane handler spanning HBM + tiers (tier-aware counterpart of
    ``transfer.serve_kv_export_bulk``) — without this, the PREFERRED
    transport would silently truncate chains at the first tier-resident
    block."""
    import asyncio as _aio

    from dynamo_tpu.engine.transfer import resolve_wire

    def handler(payload):
        payload = payload or {}
        hashes = list(payload.get("block_hashes", []))
        # merged frames always — tier-resident blocks are unsharded host
        # bytes (see serve_tiered_kv_export)
        layout, per, crc, _shards = resolve_wire(payload, 2)
        fut = _aio.run_coroutine_threadsafe(
            tiered.engine.run_exclusive(tiered_export_frames, tiered,
                                        hashes, layout, per), loop)
        frames = fut.result(timeout=120.0)
        if crc:  # checksummed in the bulk connection's thread, outside
            # the exclusive window
            from dynamo_tpu.engine.transfer import stamp_frame_crcs
            stamp_frame_crcs(frames)
        for f in frames:
            yield f.obj, f.raw

    return handler


__all__ = ["TieredEngine", "TieredKvConfig", "serve_tiered_kv_export",
           "serve_tiered_kv_export_bulk", "tiered_export_frames",
           "collect_tiered_blocks"]
