"""Lookahead KV tier promotion: the packing-prefetch scheduler.

Paper: "Architecting Long-Context LLM Acceleration with Packing-Prefetch
Scheduler and Ultra-Large Capacity On-Chip Memories" (PAPERS.md) — predict
which KV blocks the next compute window needs and stage them ahead of it,
packing compute and prefetch concurrently instead of serializing them.

Before this module, ``TieredEngine.generate`` promoted G2/G3 blocks
SYNCHRONOUSLY inside the engine's exclusive window, hard-capped at
``max_onboard_blocks`` precisely because onboarding blocked admission — a
100k-token tier-resident prompt either stalled every other request behind
one giant inject or recomputed most of its prefix. Here promotion becomes
pipelined lookahead:

- **Admission lookahead** (``PrefetchScheduler.admit``): when a request
  arrives, compute its block hashes, probe HBM/host/disk residency, onboard
  only the FIRST prefill chunk's blocks synchronously (so the scheduler's
  one prefix-match at admission sees the head of the chain), and start a
  background task streaming the rest through the staged
  ``InjectPipeline`` (PR 5): bounded donated scatters outside the hot
  path, decode steps interleaving between commit windows.
- **Cursor-paced depth** : the task promotes in chunk order within a
  bytes-budgeted window (``DYN_KV_PREFETCH_DEPTH``) ahead of the request's
  chunked-prefill cursor — never unboundedly ahead, never behind. Blocks
  that land are adopted mid-prefill by ``Scheduler._adopt_resident``
  (the admission hook half of this subsystem) instead of recomputed.
- **Pinning**: each commit window pins its blocks in the SAME exclusive
  window that committed them (``ExportLeaseManager.grant_sync``,
  ``kind="prefetch"`` — the PR 6 lease machinery, sharing the
  half-allocator hard cap with export leases), so LRU eviction pressure
  can never drop a promoted block before the request claims it. Pins are
  released when the request finishes or aborts; the lease TTL is the
  crash backstop.

Tier reads (including slow disk IO and the disk->host promote-on-use
demotion writes) run on a worker thread via the tiers' own locking —
"packing and prefetching concurrently" per the paper.
"""

from __future__ import annotations

import asyncio
import logging
import os
import time
from typing import TYPE_CHECKING, Dict, List, Optional, Tuple

import numpy as np

from dynamo_tpu.engine.transfer import (
    InjectPipeline,
    _inject_data,
    _runtime_cfg,
    export_ttl_s,
    get_export_leases,
)
from dynamo_tpu.tokens import compute_block_hash_for_seq
from dynamo_tpu.utils.tracing import get_tracer

if TYPE_CHECKING:  # pragma: no cover — import cycle guard
    from dynamo_tpu.engine.transfer import BlockPayload
    from dynamo_tpu.kvbm.manager import TieredEngine

logger = logging.getLogger(__name__)

# default lookahead window (bytes of KV promoted ahead of the prefill
# cursor); DYN_KV_PREFETCH_DEPTH / RuntimeConfig.kv_prefetch_depth override
DEFAULT_PREFETCH_DEPTH = 64 * 1024 * 1024

# cursor poll interval while the lookahead window is full (the prefill
# cursor advances once per engine step; polling faster buys nothing)
_PACE_POLL_S = 0.005


def prefetch_depth_bytes() -> int:
    """Resolve the lookahead depth: RuntimeConfig ``kv_prefetch_depth``
    (TOML / ``DYN_RUNTIME_*``), then the short-form ``DYN_KV_PREFETCH_DEPTH``
    env wins. ``0`` disables the prefetcher entirely (the tiered engine
    falls back to the bounded synchronous onboard path)."""
    depth = DEFAULT_PREFETCH_DEPTH
    try:
        depth = int(_runtime_cfg().kv_prefetch_depth)
    except Exception:  # noqa: BLE001 — a bad config must not break serving
        logger.warning("bad runtime config; kv prefetch depth falls back "
                       "to %d", depth, exc_info=True)
    raw = os.environ.get("DYN_KV_PREFETCH_DEPTH")
    if raw is not None:
        try:
            depth = int(raw)
        except (TypeError, ValueError):
            logger.warning("malformed DYN_KV_PREFETCH_DEPTH %r; using %d",
                           raw, depth)
    return max(0, depth)


def _block_bytes(engine) -> int:
    """Bytes of one KV block in this engine's cache geometry."""
    ref = engine.pages[0] if isinstance(engine.pages, list) else engine.pages
    L = (len(engine.pages) if isinstance(engine.pages, list)
         else engine.pages.shape[0])
    shape = (L,) + tuple(ref.shape[-4:])  # [L, 2, Hkv, ps, Dh]
    return int(np.prod(shape)) * np.dtype(ref.dtype).itemsize


class PrefetchScheduler:
    """Per-``TieredEngine`` promotion scheduler; one ``PrefetchHandle``
    per in-flight request doing lookahead."""

    def __init__(self, tiered: "TieredEngine",
                 depth_bytes: Optional[int] = None):
        self.tiered = tiered
        self.engine = tiered.engine
        self.depth_bytes = (prefetch_depth_bytes() if depth_bytes is None
                            else int(depth_bytes))
        # counters (single event-loop/exclusive-thread writers; reads are
        # advisory for stats)
        self.hits = 0            # blocks promoted from a tier ahead of need
        self.late = 0            # promotions that lost the race (the block
        #                          was already resident — recomputed by the
        #                          cursor or injected by a sibling — or no
        #                          free pages remained for it)
        self.misses = 0          # planned blocks that fell out of every
        #                          tier before promotion reached them
        self.evicted_pinned = 0  # canary: pinned blocks missing from HBM
        #                          at release time (must stay 0 — pinned
        #                          pages are refcounted and unevictable)
        self.promoted_bytes = 0
        self.inflight = 0        # handles with a live promotion task

    # -- admission hook ----------------------------------------------------

    async def admit(self, request) -> Optional["PrefetchHandle"]:
        """Admission lookahead for one request: bounded synchronous onboard
        of the FIRST prefill chunk's blocks, then a background promotion
        task for the rest. Returns a handle the caller must ``close()``
        when the request finishes or aborts (releases the pins), or None
        when there is nothing to prefetch."""
        engine = self.engine
        token_ids = request.token_ids
        page_size = engine.allocator.page_size
        hashes = compute_block_hash_for_seq(token_ids, page_size)
        if not hashes:
            return None
        chunk_blocks = max(
            1, engine.scheduler.cfg.max_prefill_chunk // page_size)
        cap = min(chunk_blocks, self.tiered.cfg.max_onboard_blocks)
        # first-chunk fast path: what remains of the old synchronous
        # onboard — small enough that admission latency stays bounded,
        # and HOST-tier only (a wedged disk must never stall the step
        # loop this runs serialized with; disk blocks promote async).
        # Passing the precomputed chain keeps a 100k-token hash walk out
        # of the exclusive window.
        await engine.run_exclusive(self.tiered._onboard_for, token_ids,
                                   cap, True, hashes)
        if self.depth_bytes <= 0:
            return None
        # leave >=1 token to compute (the admission/adoption rule)
        limit = (len(token_ids) - 1) // page_size
        # residency walk (advisory — the commit path re-filters): a block
        # in NO tier breaks the chain; everything past it is unusable
        with self.tiered._pending_lock:
            pending = set(self.tiered._pending_hashes)
        resident = engine.allocator._by_hash
        host, disk = self.tiered.host, self.tiered.disk
        plan: List[Tuple[int, int]] = []
        with self.tiered._tier_lock:
            for i in range(limit):
                h = hashes[i]
                if h in resident:
                    continue
                if (h in host or (disk is not None and h in disk)
                        or h in pending):
                    plan.append((i, h))
                else:
                    # chain gap: blocks past it are unusable (a cold
                    # prompt is not a "miss" — it was never promotable)
                    break
        if not plan:
            return None
        return PrefetchHandle(self, request.request_id or "", plan,
                              page_size, chunk_blocks)

    # -- tier side (worker thread) -----------------------------------------

    def _collect(self, hashes: List[int]) -> List["BlockPayload"]:
        """Read one promotion batch out of the tiers (worker thread; slow
        disk IO happens outside the host-tier lock via ``DiskTier``'s own
        locking). Stops at the first miss — later blocks are useless
        without their parents. A hash still sitting in the spill queue is
        flushed first (onboarding must observe completed offloads)."""
        t = self.tiered
        out: List["BlockPayload"] = []
        for h in hashes:
            with t._pending_lock:
                pending = h in t._pending_hashes
            if pending:
                t.flush_spills()
            blk = t._lookup(h)
            if blk is None:
                break
            out.append(blk)
        return out

    def stats(self) -> Dict[str, float]:
        mgr = get_export_leases(self.engine)
        pinned = (mgr.pinned_pages_kind("prefetch")
                  if mgr is not None else 0)
        return {
            "kvbm_prefetch_hits": self.hits,
            "kvbm_prefetch_late": self.late,
            "kvbm_prefetch_misses": self.misses,
            "kvbm_prefetch_evicted_pinned": self.evicted_pinned,
            "kvbm_prefetch_bytes": self.promoted_bytes,
            "kvbm_prefetch_pinned_pages": pinned,
            "kvbm_prefetch_inflight": self.inflight,
        }


class PrefetchHandle:
    """One request's lookahead promotion: a background task streaming tier
    blocks through an ``InjectPipeline`` paced behind the prefill cursor,
    pinning each commit window until ``close()``."""

    def __init__(self, sched: PrefetchScheduler, request_id: str,
                 plan: List[Tuple[int, int]], page_size: int,
                 chunk_blocks: int):
        self.sched = sched
        self.engine = sched.engine
        self.request_id = request_id
        self.plan = plan                      # [(block_index, hash), ...]
        self.page_size = page_size
        self.block_bytes = max(1, _block_bytes(self.engine))
        # batch = FOUR prefill chunks per promotion iteration: commits
        # land in the exclusive gaps BETWEEN engine steps, and the compute
        # cursor advances one chunk per step — a batch no bigger than a
        # chunk could never outrun it, while a much larger batch stages so
        # long the cursor passes it before the commit lands (measured on
        # the bench long-context leg: 2 chunks -> 0.46 hit rate, 4 ->
        # 0.73, 8 -> 0.18). Four gains ~3 chunks of ground per step.
        self.chunk_blocks = max(1, chunk_blocks)
        self.batch_blocks = 4 * self.chunk_blocks
        self.depth_blocks = max(self.batch_blocks,
                                sched.depth_bytes // self.block_bytes)
        # commit window = the whole batch: ordered flushes land ONE
        # commit per exclusive gap, and gaps come once per engine step —
        # a window smaller than the chunk the step just computed can
        # never gain on the cursor, and halving the window measurably
        # halves the ground gained (bench leg: 0.45 vs 0.72 hit rate,
        # 2x the 32k TTFT). Cost: the pipeline's double-buffered host
        # staging is 2x the batch's bytes (~4 chunks of KV); the
        # exclusive stall per window is a scatter of 4 chunks' blocks —
        # comparable to the prefill step the scheduler already
        # interleaves decode with.
        self.window = self.batch_blocks
        self.hits = 0
        self.late = 0
        self._mgr = get_export_leases(self.engine)
        self._lease_ids: List[int] = []
        self._pinned_hashes: set = set()
        self._closed = False
        self._seen_active = False
        # current=False: this span outlives the admission call that opened
        # it (it finishes when the promotion task does) — it must not
        # become the ambient parent of the request's own stage spans
        self._span = get_tracer().start_span("kv_prefetch", attrs={
            "request_id": request_id,
            "planned_blocks": len(plan),
            "depth_bytes": sched.depth_bytes,
            "depth_blocks": self.depth_blocks,
        }, current=False)
        sched.inflight += 1
        self._task = asyncio.create_task(self._run())

    # -- commit callback (engine exclusive worker thread) ------------------

    def _commit(self, eng, metas, data) -> int:
        n = _inject_data(eng, metas, data, self.window)
        self.hits += n
        self.late += len(metas) - n
        self.sched.hits += n
        self.sched.late += len(metas) - n
        self.sched.promoted_bytes += n * self.block_bytes
        self.sched.tiered.onboarded += n  # prefetched blocks ARE onboards
        if self._mgr is not None and metas:
            # pin in the SAME exclusive window that committed: eviction
            # pressure can never snatch a block between commit and pin
            lease, npinned = self._mgr.grant_sync(
                [m[0] for m in metas], kind="prefetch")
            if lease is not None:
                self._lease_ids.append(lease)
                self._pinned_hashes.update(m[0] for m in metas[:npinned])
        return n

    # -- pacing ------------------------------------------------------------

    def _cursor_block(self) -> Optional[int]:
        """The request's prefill cursor in blocks (advisory read), or None
        once the request has left the engine (finished/aborted)."""
        seq = self.engine.scheduler.active.get(self.request_id)
        if seq is None:
            return None if self._seen_active else 0
        self._seen_active = True
        return seq.num_computed // self.page_size

    async def _run(self) -> None:
        t0 = time.perf_counter()
        pipe = InjectPipeline(self.engine, window=self.window,
                              commit=self._commit)
        aborted = False
        try:
            pos = 0
            while pos < len(self.plan) and not self._closed:
                cursor = self._cursor_block()
                if cursor is None:
                    aborted = True
                    break
                lookahead_end = cursor + self.depth_blocks
                # concede a one-chunk guard ahead of the cursor: blocks
                # the NEXT prefill step will compute before any commit of
                # ours could land — promoting them would be duplicated
                # work that always loses the race. Compute eats the guard
                # chunk while promotion covers everything past it (the
                # paper's packing: compute window k, prefetch window k+1).
                # No guard before the request is ADMITTED: nothing is
                # computing yet, so even first-chunk blocks the host-only
                # fast path skipped (disk-resident, or parked in the
                # spill queue) get a genuine head start — this is also
                # the only promotion path short disk-resident prompts
                # have.
                frontier = cursor + (self.chunk_blocks
                                     if self._seen_active else 0)
                resident = self.engine.allocator._by_hash  # advisory
                batch: List[int] = []
                while (pos < len(self.plan)
                       and self.plan[pos][0] < frontier):
                    _i, h = self.plan[pos]
                    pos += 1
                    if h not in resident:
                        self.late += 1        # conceded to the cursor
                        self.sched.late += 1
                while (pos < len(self.plan)
                       and len(batch) < self.batch_blocks
                       and self.plan[pos][0] < lookahead_end):
                    _i, h = self.plan[pos]
                    pos += 1
                    if h in resident:
                        # the cursor (or a sibling request) got there
                        # first: promotion would be filtered anyway
                        self.late += 1
                        self.sched.late += 1
                        continue
                    batch.append(h)
                if not batch:
                    if pos >= len(self.plan):
                        break
                    await asyncio.sleep(_PACE_POLL_S)  # window full: wait
                    continue                           # for the cursor
                blocks = await asyncio.to_thread(self.sched._collect,
                                                 batch)
                if blocks:
                    await pipe.add_blocks(blocks)
                if len(blocks) < len(batch):
                    # a needed block fell out of every tier mid-flight:
                    # the chain is broken past it
                    self.sched.misses += len(batch) - len(blocks)
                    break
            await pipe.finish()
        except asyncio.CancelledError:
            aborted = True
            await pipe.drain()
        except Exception as e:  # noqa: BLE001 — prefetch must never fail
            # the request; the cursor just recomputes what didn't land
            self._span.set_error(str(e))
            logger.exception("kv prefetch promotion failed")
            await pipe.drain()
        finally:
            if self._mgr is not None and self._lease_ids:
                # crash backstop: if close() never runs (process dying,
                # handle leaked), the TTL sweep reclaims the pins
                try:
                    self._mgr.arm_sweep(export_ttl_s())
                except Exception:  # noqa: BLE001
                    pass
            self.sched.inflight -= 1
            self._span.set_attr("promoted_blocks", self.hits)
            self._span.set_attr("bytes", self.hits * self.block_bytes)
            self._span.set_attr("late", self.late)
            self._span.set_attr("pinned_pages", len(self._pinned_hashes))
            self._span.set_attr("promote_ms", round(
                (time.perf_counter() - t0) * 1e3, 1))
            if aborted:
                self._span.set_attr("aborted", True)
            self._span.finish()

    # -- lifecycle ---------------------------------------------------------

    async def wait(self, timeout: float = 30.0) -> None:
        """Test hook: block until the promotion task finished."""
        await asyncio.wait_for(asyncio.shield(self._task), timeout)

    async def close(self) -> None:
        """Stop any in-flight promotion and release the pins — called when
        the request finishes (its own page refs now protect the prefix) or
        aborts (the blocks return to the ordinary LRU). Idempotent."""
        if self._closed:
            return
        self._closed = True
        if not self._task.done():
            self._task.cancel()
        try:
            await self._task
        except (asyncio.CancelledError, Exception):  # noqa: BLE001
            pass
        # canary BEFORE release, and only while every lease is still LIVE:
        # a pinned block missing from HBM then means the pin machinery
        # failed (refcounted pages are unevictable). A lease the TTL
        # sweep already reclaimed (request outlived DYN_KV_EXPORT_TTL_S)
        # legitimately un-pinned its pages — not a canary event.
        if (self._mgr is not None and self._lease_ids
                and all(self._mgr.holds(lid) for lid in self._lease_ids)):
            resident = self.engine.allocator._by_hash
            gone = sum(1 for h in self._pinned_hashes
                       if h not in resident)
            if gone:
                self.sched.evicted_pinned += gone
                logger.warning(
                    "%d prefetched block(s) vanished while pinned", gone)
        await self._release_pins()

    async def _release_pins(self) -> None:
        mgr, eng = self._mgr, self.engine
        if mgr is None:
            return
        leases, self._lease_ids = self._lease_ids, []
        for lid in leases:
            try:
                if (getattr(eng, "_stopping", False)
                        or eng._loop_task is None
                        or eng._loop_task.done()):
                    # loop stopped/dead: run_exclusive would restart it
                    mgr.release_detached(lid)
                else:
                    await mgr.release(lid)
            except Exception:  # noqa: BLE001 — TTL covers a failed release
                mgr.release_detached(lid)


__all__ = ["PrefetchScheduler", "PrefetchHandle", "prefetch_depth_bytes",
           "DEFAULT_PREFETCH_DEPTH"]
