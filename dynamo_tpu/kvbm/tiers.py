"""Host-RAM and disk block pools: byte-budgeted LRU keyed by block hash.

Parity in role with the reference's G2/G3 pools (``block_manager/pool/*``,
``storage/{cuda,disk}.rs``): bounded capacity, LRU eviction, lookup by
sequence/content hash. Demotion (G2 overflow -> G3) is the offload manager's
job (``manager.py``); each tier only stores and evicts.

Thread model: ``HostTier`` is NOT thread-safe — the manager's tier lock
guards it. ``DiskTier`` locks internally (index/byte accounting under its
own lock, file reads outside it) so promotion reads from the prefetch
scheduler's worker thread never serialize host-tier lookups behind disk IO.

Integrity: every ``DiskTier.put`` stamps a crc32 of the block bytes into
its index entry (the wire-v4 checksum discipline, ``engine/transfer``);
``get`` verifies length AND checksum before returning — a truncated or
corrupted file (crash mid-write, bit rot) is treated as a MISS and the
entry evicted, never injected as garbage KV. ``DYN_KV_DISK_CRC=0``
disables the stamp/verify (length is still checked).
"""

from __future__ import annotations

import logging
import os
import threading
import zlib
from collections import OrderedDict
from typing import List, Optional, Tuple

import numpy as np

from dynamo_tpu.engine.transfer import BlockPayload

logger = logging.getLogger(__name__)


def disk_crc_enabled() -> bool:
    """Per-entry crc32 on disk-tier blocks (``DYN_KV_DISK_CRC=0``
    disables — entries written without a checksum skip verification)."""
    return os.environ.get("DYN_KV_DISK_CRC", "1") not in ("0", "false", "")


class HostTier:
    """G2: host-RAM LRU of block payloads."""

    def __init__(self, budget_bytes: int):
        self.budget = budget_bytes
        self.used = 0
        self._blocks: "OrderedDict[int, BlockPayload]" = OrderedDict()

    def __contains__(self, block_hash: int) -> bool:
        return block_hash in self._blocks

    def __len__(self) -> int:
        return len(self._blocks)

    def put(self, block: BlockPayload) -> List[BlockPayload]:
        """Insert; returns demoted blocks evicted to make room."""
        size = block.data.nbytes
        if size > self.budget:
            return [block]  # doesn't fit at all: demote immediately
        if block.block_hash in self._blocks:
            self._blocks.move_to_end(block.block_hash)
            return []
        demoted: List[BlockPayload] = []
        while self.used + size > self.budget and self._blocks:
            _h, old = self._blocks.popitem(last=False)
            self.used -= old.data.nbytes
            demoted.append(old)
        self._blocks[block.block_hash] = block
        self.used += size
        return demoted

    def get(self, block_hash: int) -> Optional[BlockPayload]:
        blk = self._blocks.get(block_hash)
        if blk is not None:
            self._blocks.move_to_end(block_hash)
        return blk

    def pop(self, block_hash: int) -> Optional[BlockPayload]:
        blk = self._blocks.pop(block_hash, None)
        if blk is not None:
            self.used -= blk.data.nbytes
        return blk


class DiskTier:
    """G3: one ``.kvblk`` file per block under a directory, LRU by
    insertion/access order, byte-budgeted, crc-checked on read."""

    def __init__(self, path: str, budget_bytes: int):
        self.path = path
        self.budget = budget_bytes
        self.used = 0
        os.makedirs(path, exist_ok=True)
        # hash -> (filename, nbytes, local_hash, parent_hash, dtype,
        #          shape, crc32|None)
        self._index: "OrderedDict[int, Tuple]" = OrderedDict()
        # guards _index/used; file reads happen OUTSIDE it so a slow disk
        # only stalls the reader, not every other tier operation
        self._lock = threading.RLock()
        self.corrupt_dropped = 0

    def __contains__(self, block_hash: int) -> bool:
        with self._lock:
            return block_hash in self._index

    def __len__(self) -> int:
        with self._lock:
            return len(self._index)

    def _file(self, block_hash: int) -> str:
        return os.path.join(self.path, f"{block_hash:016x}.kvblk")

    def put(self, block: BlockPayload) -> None:
        size = block.data.nbytes
        if size > self.budget:
            return
        with self._lock:
            if block.block_hash in self._index:
                self._index.move_to_end(block.block_hash)
                return
            evict: List[str] = []
            while self.used + size > self.budget and self._index:
                h, (fn, nbytes, *_rest) = self._index.popitem(last=False)
                self.used -= nbytes
                evict.append(fn)
            # reserve the bytes BEFORE the write so a concurrent put can't
            # overshoot the budget while this file is still streaming out
            self.used += size
        for fn in evict:
            try:
                os.unlink(fn)
            except OSError:
                pass
        raw = block.data.tobytes()
        crc = (zlib.crc32(raw) & 0xFFFFFFFF) if disk_crc_enabled() else None
        fn = self._file(block.block_hash)
        try:
            with open(fn, "wb") as f:
                f.write(raw)
        except OSError:
            logger.exception("disk tier write failed; block dropped")
            with self._lock:
                self.used -= size
            return
        with self._lock:
            if block.block_hash in self._index:
                # raced another writer of the same content-addressed block
                # (spill thread vs promotion write-back): one file, one
                # entry — give back this writer's byte reservation
                self.used -= size
                self._index.move_to_end(block.block_hash)
                return
            self._index[block.block_hash] = (
                fn, size, block.local_hash, block.parent_hash,
                str(block.data.dtype), block.data.shape, crc)

    def _evict_entry(self, block_hash: int, unlink: bool = True) -> None:
        with self._lock:
            meta = self._index.pop(block_hash, None)
            if meta is None:
                return
            self.used -= meta[1]
        if unlink:
            try:
                os.unlink(meta[0])
            except OSError:
                pass

    def get(self, block_hash: int) -> Optional[BlockPayload]:
        with self._lock:
            meta = self._index.get(block_hash)
        if meta is None:
            return None
        fn, nbytes, local, parent, dtype, shape, crc = meta
        try:
            with open(fn, "rb") as f:  # slow IO: outside the index lock
                raw = f.read()
        except OSError:
            self._evict_entry(block_hash, unlink=False)
            return None
        if len(raw) != nbytes or (
                crc is not None
                and (zlib.crc32(raw) & 0xFFFFFFFF) != crc):
            # truncated (crash mid-write) or corrupted on disk: a MISS,
            # never injected — evict the entry so it can't hit again
            logger.warning(
                "disk tier entry %016x corrupt (%d bytes, want %d, crc "
                "%s): dropped", block_hash, len(raw), nbytes,
                "mismatch" if len(raw) == nbytes else "n/a")
            self.corrupt_dropped += 1
            self._evict_entry(block_hash)
            return None
        with self._lock:
            if block_hash in self._index:
                self._index.move_to_end(block_hash)
        arr = np.frombuffer(raw, dtype=np.dtype(dtype))
        return BlockPayload(block_hash=block_hash, local_hash=local,
                            parent_hash=parent, data=arr.reshape(shape))


__all__ = ["HostTier", "DiskTier", "disk_crc_enabled"]
