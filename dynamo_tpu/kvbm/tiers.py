"""Host-RAM and disk block pools: byte-budgeted LRU keyed by block hash.

Parity in role with the reference's G2/G3 pools (``block_manager/pool/*``,
``storage/{cuda,disk}.rs``): bounded capacity, LRU eviction, lookup by
sequence/content hash. Demotion (G2 overflow -> G3) is the offload manager's
job (``manager.py``); each tier only stores and evicts.
"""

from __future__ import annotations

import logging
import os
from collections import OrderedDict
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import numpy as np

from dynamo_tpu.engine.transfer import BlockPayload

logger = logging.getLogger(__name__)


class HostTier:
    """G2: host-RAM LRU of block payloads."""

    def __init__(self, budget_bytes: int):
        self.budget = budget_bytes
        self.used = 0
        self._blocks: "OrderedDict[int, BlockPayload]" = OrderedDict()

    def __contains__(self, block_hash: int) -> bool:
        return block_hash in self._blocks

    def __len__(self) -> int:
        return len(self._blocks)

    def put(self, block: BlockPayload) -> List[BlockPayload]:
        """Insert; returns demoted blocks evicted to make room."""
        size = block.data.nbytes
        if size > self.budget:
            return [block]  # doesn't fit at all: demote immediately
        if block.block_hash in self._blocks:
            self._blocks.move_to_end(block.block_hash)
            return []
        demoted: List[BlockPayload] = []
        while self.used + size > self.budget and self._blocks:
            _h, old = self._blocks.popitem(last=False)
            self.used -= old.data.nbytes
            demoted.append(old)
        self._blocks[block.block_hash] = block
        self.used += size
        return demoted

    def get(self, block_hash: int) -> Optional[BlockPayload]:
        blk = self._blocks.get(block_hash)
        if blk is not None:
            self._blocks.move_to_end(block_hash)
        return blk

    def pop(self, block_hash: int) -> Optional[BlockPayload]:
        blk = self._blocks.pop(block_hash, None)
        if blk is not None:
            self.used -= blk.data.nbytes
        return blk


class DiskTier:
    """G3: one ``.npy``-style file per block under a directory, LRU by
    insertion/access order, byte-budgeted."""

    def __init__(self, path: str, budget_bytes: int):
        self.path = path
        self.budget = budget_bytes
        self.used = 0
        os.makedirs(path, exist_ok=True)
        # hash -> (filename, nbytes, local_hash, parent_hash, dtype, shape)
        self._index: "OrderedDict[int, Tuple]" = OrderedDict()

    def __contains__(self, block_hash: int) -> bool:
        return block_hash in self._index

    def __len__(self) -> int:
        return len(self._index)

    def _file(self, block_hash: int) -> str:
        return os.path.join(self.path, f"{block_hash:016x}.kvblk")

    def put(self, block: BlockPayload) -> None:
        size = block.data.nbytes
        if size > self.budget:
            return
        if block.block_hash in self._index:
            self._index.move_to_end(block.block_hash)
            return
        while self.used + size > self.budget and self._index:
            h, (fn, nbytes, *_rest) = self._index.popitem(last=False)
            self.used -= nbytes
            try:
                os.unlink(fn)
            except OSError:
                pass
        fn = self._file(block.block_hash)
        with open(fn, "wb") as f:
            f.write(block.data.tobytes())
        self._index[block.block_hash] = (
            fn, size, block.local_hash, block.parent_hash,
            str(block.data.dtype), block.data.shape)
        self.used += size

    def get(self, block_hash: int) -> Optional[BlockPayload]:
        meta = self._index.get(block_hash)
        if meta is None:
            return None
        fn, _nbytes, local, parent, dtype, shape = meta
        try:
            with open(fn, "rb") as f:
                arr = np.frombuffer(f.read(), dtype=np.dtype(dtype))
        except OSError:
            self._index.pop(block_hash, None)
            return None
        self._index.move_to_end(block_hash)
        return BlockPayload(block_hash=block_hash, local_hash=local,
                            parent_hash=parent, data=arr.reshape(shape))


__all__ = ["HostTier", "DiskTier"]
