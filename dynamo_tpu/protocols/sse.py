"""Server-Sent Events codec for OpenAI-style streaming responses.

Parity: reference ``lib/llm/src/protocols/codec.rs`` (755 LoC SSE codec).
"""

from __future__ import annotations

import json
from dataclasses import dataclass
from typing import Any, Iterator, List, Optional

DONE_SENTINEL = "[DONE]"


@dataclass
class SseEvent:
    data: Optional[str] = None
    event: Optional[str] = None
    id: Optional[str] = None
    comments: Optional[List[str]] = None

    @property
    def is_done(self) -> bool:
        return self.data is not None and self.data.strip() == DONE_SENTINEL

    def encode(self) -> bytes:
        lines: List[str] = []
        for c in self.comments or []:
            lines.append(f": {c}")
        if self.event:
            lines.append(f"event: {self.event}")
        if self.id:
            lines.append(f"id: {self.id}")
        if self.data is not None:
            for dline in self.data.split("\n"):
                lines.append(f"data: {dline}")
        return ("\n".join(lines) + "\n\n").encode()

    def json(self) -> Any:
        if self.data is None or self.is_done:
            return None
        return json.loads(self.data)


def encode_data(obj: Any) -> bytes:
    """Encode a JSON-serializable object as one SSE data event."""
    return SseEvent(data=json.dumps(obj, separators=(",", ":"))).encode()


def encode_done() -> bytes:
    return SseEvent(data=DONE_SENTINEL).encode()


class SseDecoder:
    """Incremental SSE parser: feed bytes, iterate complete events."""

    def __init__(self) -> None:
        self._buf = b""

    def feed(self, chunk: bytes) -> Iterator[SseEvent]:
        self._buf += chunk
        # normalize CRLF once per feed; events are separated by a blank line
        while True:
            norm = self._buf.replace(b"\r\n", b"\n")
            sep = norm.find(b"\n\n")
            if sep < 0:
                self._buf = norm
                return
            raw, self._buf = norm[:sep], norm[sep + 2 :]
            ev = self._parse(raw.decode("utf-8", errors="replace"))
            if ev is not None:
                yield ev

    @staticmethod
    def _parse(raw: str) -> Optional[SseEvent]:
        data_lines: List[str] = []
        comments: List[str] = []
        event = None
        eid = None
        for line in raw.split("\n"):
            if not line:
                continue
            if line.startswith(":"):
                comments.append(line[1:].lstrip())
                continue
            key, _, value = line.partition(":")
            value = value[1:] if value.startswith(" ") else value
            if key == "data":
                data_lines.append(value)
            elif key == "event":
                event = value
            elif key == "id":
                eid = value
        if not data_lines and event is None and eid is None and not comments:
            return None
        return SseEvent(
            data="\n".join(data_lines) if data_lines else None,
            event=event,
            id=eid,
            comments=comments or None,
        )


__all__ = ["SseEvent", "SseDecoder", "encode_data", "encode_done", "DONE_SENTINEL"]
