"""OpenAI-compatible API types (chat completions, completions, embeddings).

Pydantic models used by the HTTP frontend for request validation and response
serialization, including streaming delta chunks.  The ``nvext``-style extension
field is carried as ``extensions`` (annotations etc.).

Parity: reference ``lib/llm/src/protocols/openai/`` (chat_completions,
completions, embeddings, nvext) — see SURVEY.md §2.2.
"""

from __future__ import annotations

import time
import uuid
from typing import Any, Dict, List, Literal, Optional, Union

from pydantic import BaseModel, ConfigDict, Field


class Extensions(BaseModel):
    """Framework extension fields (reference: ``nvext.rs``)."""

    model_config = ConfigDict(extra="allow")
    annotations: Optional[List[str]] = None
    ignore_eos: Optional[bool] = None
    greed_sampling: Optional[bool] = None
    # per-request end-to-end deadline override (seconds from arrival);
    # takes precedence over the X-Request-Timeout header and the service's
    # configured default
    timeout_s: Optional[float] = None


class ChatMessage(BaseModel):
    model_config = ConfigDict(extra="allow")
    role: str
    content: Optional[Union[str, List[Dict[str, Any]]]] = None
    name: Optional[str] = None
    tool_calls: Optional[List[Dict[str, Any]]] = None
    tool_call_id: Optional[str] = None

    def text_content(self) -> str:
        if self.content is None:
            return ""
        if isinstance(self.content, str):
            return self.content
        # multimodal content parts: concatenate text parts
        return "".join(
            p.get("text", "") for p in self.content if isinstance(p, dict) and p.get("type") == "text"
        )


class StreamOptions(BaseModel):
    include_usage: bool = False


class ChatCompletionRequest(BaseModel):
    model_config = ConfigDict(extra="allow")
    model: str
    messages: List[ChatMessage]
    temperature: Optional[float] = None
    top_p: Optional[float] = None
    top_k: Optional[int] = None  # extension (vLLM-style)
    n: int = 1
    stream: bool = False
    stream_options: Optional[StreamOptions] = None
    stop: Optional[Union[str, List[str]]] = None
    max_tokens: Optional[int] = None
    max_completion_tokens: Optional[int] = None
    min_tokens: Optional[int] = None  # extension
    presence_penalty: Optional[float] = None
    frequency_penalty: Optional[float] = None
    repetition_penalty: Optional[float] = None  # extension
    logit_bias: Optional[Dict[str, float]] = None
    min_p: Optional[float] = Field(default=None, ge=0.0, le=1.0)  # vLLM-style
    logprobs: Optional[bool] = None
    top_logprobs: Optional[int] = None
    seed: Optional[int] = None
    user: Optional[str] = None
    tools: Optional[List[Dict[str, Any]]] = None
    tool_choice: Optional[Union[str, Dict[str, Any]]] = None
    # OpenAI structured outputs: {"type": "text" | "json_object"} or
    # {"type": "json_schema", "json_schema": {"schema": {...}, ...}}
    response_format: Optional[Dict[str, Any]] = None
    nvext: Optional[Extensions] = None

    def stop_list(self) -> Optional[List[str]]:
        if self.stop is None:
            return None
        return [self.stop] if isinstance(self.stop, str) else list(self.stop)

    def effective_max_tokens(self) -> Optional[int]:
        return self.max_completion_tokens or self.max_tokens

    def guided_spec(self) -> Optional[Dict[str, Any]]:
        """Map response_format to the engine's guided-decoding spec
        (``engine/guided.py``); raises ValueError on malformed input."""
        rf = self.response_format
        if not rf:
            return None
        kind = rf.get("type")
        if kind in (None, "text"):
            return None
        if kind == "json_object":
            return {"mode": "json"}
        if kind == "json_schema":
            js = rf.get("json_schema") or {}
            if not isinstance(js, dict):
                raise ValueError(
                    "response_format.json_schema must be an object")
            schema = js.get("schema")
            if not isinstance(schema, dict):
                raise ValueError(
                    "response_format.json_schema.schema must be an object")
            return {"mode": "json_schema", "schema": schema}
        raise ValueError(f"unsupported response_format type {kind!r}")


class CompletionRequest(BaseModel):
    model_config = ConfigDict(extra="allow")
    model: str
    prompt: Union[str, List[str], List[int], List[List[int]]]
    suffix: Optional[str] = None
    max_tokens: Optional[int] = 16
    min_tokens: Optional[int] = None
    temperature: Optional[float] = None
    top_p: Optional[float] = None
    top_k: Optional[int] = None
    n: int = 1
    stream: bool = False
    stream_options: Optional[StreamOptions] = None
    logprobs: Optional[int] = None
    echo: bool = False
    stop: Optional[Union[str, List[str]]] = None
    presence_penalty: Optional[float] = None
    frequency_penalty: Optional[float] = None
    repetition_penalty: Optional[float] = None
    logit_bias: Optional[Dict[str, float]] = None
    min_p: Optional[float] = Field(default=None, ge=0.0, le=1.0)  # vLLM-style
    seed: Optional[int] = None
    user: Optional[str] = None
    nvext: Optional[Extensions] = None

    def stop_list(self) -> Optional[List[str]]:
        if self.stop is None:
            return None
        return [self.stop] if isinstance(self.stop, str) else list(self.stop)


class EmbeddingRequest(BaseModel):
    model_config = ConfigDict(extra="allow")
    model: str
    input: Union[str, List[str], List[int], List[List[int]]]
    encoding_format: Literal["float", "base64"] = "float"
    dimensions: Optional[int] = None
    user: Optional[str] = None


class Usage(BaseModel):
    prompt_tokens: int = 0
    completion_tokens: int = 0
    total_tokens: int = 0
    prompt_tokens_details: Optional[Dict[str, int]] = None


class ChoiceLogprobs(BaseModel):
    content: Optional[List[Dict[str, Any]]] = None


class ChatChoice(BaseModel):
    index: int = 0
    message: ChatMessage
    finish_reason: Optional[str] = None
    logprobs: Optional[ChoiceLogprobs] = None


class ChatCompletionResponse(BaseModel):
    id: str
    object: Literal["chat.completion"] = "chat.completion"
    created: int
    model: str
    choices: List[ChatChoice]
    usage: Optional[Usage] = None
    system_fingerprint: Optional[str] = None


class DeltaMessage(BaseModel):
    role: Optional[str] = None
    content: Optional[str] = None
    tool_calls: Optional[List[Dict[str, Any]]] = None


class ChatChunkChoice(BaseModel):
    index: int = 0
    delta: DeltaMessage
    finish_reason: Optional[str] = None
    logprobs: Optional[ChoiceLogprobs] = None


class ChatCompletionChunk(BaseModel):
    id: str
    object: Literal["chat.completion.chunk"] = "chat.completion.chunk"
    created: int
    model: str
    choices: List[ChatChunkChoice]
    usage: Optional[Usage] = None


class CompletionChoice(BaseModel):
    index: int = 0
    text: str = ""
    finish_reason: Optional[str] = None
    logprobs: Optional[Dict[str, Any]] = None


class CompletionResponse(BaseModel):
    id: str
    object: Literal["text_completion"] = "text_completion"
    created: int
    model: str
    choices: List[CompletionChoice]
    usage: Optional[Usage] = None


class EmbeddingData(BaseModel):
    object: Literal["embedding"] = "embedding"
    index: int
    embedding: Union[List[float], str]


class EmbeddingResponse(BaseModel):
    object: Literal["list"] = "list"
    data: List[EmbeddingData]
    model: str
    usage: Optional[Usage] = None


class ModelInfo(BaseModel):
    id: str
    object: Literal["model"] = "model"
    created: int = 0
    owned_by: str = "dynamo_tpu"


class ModelList(BaseModel):
    object: Literal["list"] = "list"
    data: List[ModelInfo] = Field(default_factory=list)


def new_request_id(prefix: str = "chatcmpl") -> str:
    return f"{prefix}-{uuid.uuid4().hex}"


def now_unix() -> int:
    return int(time.time())


__all__ = [
    "Extensions",
    "ChatMessage",
    "StreamOptions",
    "ChatCompletionRequest",
    "CompletionRequest",
    "EmbeddingRequest",
    "Usage",
    "ChatChoice",
    "ChatCompletionResponse",
    "DeltaMessage",
    "ChatChunkChoice",
    "ChatCompletionChunk",
    "CompletionChoice",
    "CompletionResponse",
    "EmbeddingData",
    "EmbeddingResponse",
    "ModelInfo",
    "ModelList",
    "new_request_id",
    "now_unix",
]
