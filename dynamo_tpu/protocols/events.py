"""KV-cache event plane and worker load-metric types.

Workers publish ``KvCacheEvent``s (blocks stored / removed) on the event bus;
the KV router applies them to its radix tree.  Workers also publish
``ForwardPassMetrics`` snapshots that the router's scheduler uses for load-aware
placement.

Parity: reference ``lib/llm/src/kv_router/protocols.rs`` (``KvCacheEvent``,
``RouterEvent``, ``ForwardPassMetrics{WorkerStats, KvStats, SpecDecodeStats}``)
and ``lib/llm/src/kv_router/publisher.rs``.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional


@dataclass
class KvCacheStoredBlock:
    block_hash: int
    tokens_hash: int  # unchained local hash (diagnostics)

    def to_dict(self) -> Dict[str, Any]:
        return {"block_hash": self.block_hash, "tokens_hash": self.tokens_hash}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "KvCacheStoredBlock":
        return cls(block_hash=d["block_hash"], tokens_hash=d.get("tokens_hash", 0))


@dataclass
class KvCacheEvent:
    """One cache mutation on a worker.

    ``stored`` events carry the chained block hashes (with the parent hash so
    the indexer can attach them at the right radix-tree position); ``removed``
    events carry evicted block hashes.  ``event_id`` is a per-worker
    monotonically increasing sequence number used to detect gaps.
    """

    event_id: int = 0
    stored_blocks: List[KvCacheStoredBlock] = field(default_factory=list)
    stored_parent_hash: Optional[int] = None
    removed_block_hashes: List[int] = field(default_factory=list)
    # "all_blocks_cleared" resets the worker's subtree (e.g. /clear_kv_blocks)
    all_blocks_cleared: bool = False

    def to_dict(self) -> Dict[str, Any]:
        return {
            "event_id": self.event_id,
            "stored_blocks": [b.to_dict() for b in self.stored_blocks],
            "stored_parent_hash": self.stored_parent_hash,
            "removed_block_hashes": list(self.removed_block_hashes),
            "all_blocks_cleared": self.all_blocks_cleared,
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "KvCacheEvent":
        return cls(
            event_id=d.get("event_id", 0),
            stored_blocks=[KvCacheStoredBlock.from_dict(b) for b in d.get("stored_blocks", [])],
            stored_parent_hash=d.get("stored_parent_hash"),
            removed_block_hashes=list(d.get("removed_block_hashes", [])),
            all_blocks_cleared=bool(d.get("all_blocks_cleared", False)),
        )


@dataclass
class RouterEvent:
    """A ``KvCacheEvent`` attributed to a worker instance."""

    worker_id: int
    event: KvCacheEvent

    def to_dict(self) -> Dict[str, Any]:
        return {"worker_id": self.worker_id, "event": self.event.to_dict()}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "RouterEvent":
        return cls(worker_id=d["worker_id"], event=KvCacheEvent.from_dict(d["event"]))


@dataclass
class WorkerStats:
    request_active_slots: int = 0
    request_total_slots: int = 0
    num_requests_waiting: int = 0
    data_parallel_rank: Optional[int] = None
    # cumulative MoE dispatch overflow (token-expert assignments dropped
    # past expert capacity) — 0 on dense models/backends; a growing value
    # tells an operator that output perturbation is dispatch overflow, not
    # model behavior (extension over the reference's protocols.rs fields)
    moe_dropped_tokens: int = 0

    def to_dict(self) -> Dict[str, Any]:
        return {
            "request_active_slots": self.request_active_slots,
            "request_total_slots": self.request_total_slots,
            "num_requests_waiting": self.num_requests_waiting,
            "data_parallel_rank": self.data_parallel_rank,
            "moe_dropped_tokens": self.moe_dropped_tokens,
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "WorkerStats":
        return cls(
            request_active_slots=d.get("request_active_slots", 0),
            request_total_slots=d.get("request_total_slots", 0),
            num_requests_waiting=d.get("num_requests_waiting", 0),
            data_parallel_rank=d.get("data_parallel_rank"),
            moe_dropped_tokens=d.get("moe_dropped_tokens", 0),
        )


@dataclass
class KvStats:
    kv_active_blocks: int = 0
    kv_total_blocks: int = 0
    gpu_cache_usage_perc: float = 0.0  # name kept engine-agnostic in semantics
    gpu_prefix_cache_hit_rate: float = 0.0

    def to_dict(self) -> Dict[str, Any]:
        return {
            "kv_active_blocks": self.kv_active_blocks,
            "kv_total_blocks": self.kv_total_blocks,
            "gpu_cache_usage_perc": self.gpu_cache_usage_perc,
            "gpu_prefix_cache_hit_rate": self.gpu_prefix_cache_hit_rate,
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "KvStats":
        return cls(
            kv_active_blocks=d.get("kv_active_blocks", 0),
            kv_total_blocks=d.get("kv_total_blocks", 0),
            gpu_cache_usage_perc=d.get("gpu_cache_usage_perc", 0.0),
            gpu_prefix_cache_hit_rate=d.get("gpu_prefix_cache_hit_rate", 0.0),
        )


@dataclass
class SpecDecodeStats:
    num_spec_tokens: int = 0
    num_drafts: int = 0
    num_draft_tokens: int = 0
    num_accepted_tokens: int = 0

    def to_dict(self) -> Dict[str, Any]:
        return {
            "num_spec_tokens": self.num_spec_tokens,
            "num_drafts": self.num_drafts,
            "num_draft_tokens": self.num_draft_tokens,
            "num_accepted_tokens": self.num_accepted_tokens,
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "SpecDecodeStats":
        return cls(**{k: d.get(k, 0) for k in (
            "num_spec_tokens", "num_drafts", "num_draft_tokens", "num_accepted_tokens")})


@dataclass
class ForwardPassMetrics:
    """A worker's load snapshot, published periodically and scraped on demand.

    Parity: reference ``kv_router/protocols.rs:42-100``.
    """

    worker_stats: WorkerStats = field(default_factory=WorkerStats)
    kv_stats: KvStats = field(default_factory=KvStats)
    spec_decode_stats: Optional[SpecDecodeStats] = None

    def to_dict(self) -> Dict[str, Any]:
        d = {
            "worker_stats": self.worker_stats.to_dict(),
            "kv_stats": self.kv_stats.to_dict(),
        }
        if self.spec_decode_stats is not None:
            d["spec_decode_stats"] = self.spec_decode_stats.to_dict()
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ForwardPassMetrics":
        sd = d.get("spec_decode_stats")
        return cls(
            worker_stats=WorkerStats.from_dict(d.get("worker_stats") or {}),
            kv_stats=KvStats.from_dict(d.get("kv_stats") or {}),
            spec_decode_stats=SpecDecodeStats.from_dict(sd) if sd else None,
        )


@dataclass
class KVHitRateEvent:
    """Emitted by the router scheduler on each routing decision."""

    worker_id: int
    isl_blocks: int
    overlap_blocks: int

    def to_dict(self) -> Dict[str, Any]:
        return {
            "worker_id": self.worker_id,
            "isl_blocks": self.isl_blocks,
            "overlap_blocks": self.overlap_blocks,
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "KVHitRateEvent":
        return cls(d["worker_id"], d["isl_blocks"], d["overlap_blocks"])


__all__ = [
    "KvCacheStoredBlock",
    "KvCacheEvent",
    "RouterEvent",
    "WorkerStats",
    "KvStats",
    "SpecDecodeStats",
    "ForwardPassMetrics",
    "KVHitRateEvent",
]
