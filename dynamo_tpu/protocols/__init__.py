"""Wire protocols: internal request/response types, OpenAI API types, SSE codec,
KV-cache events and worker metrics.

Parity: reference ``lib/llm/src/protocols/`` (~5,400 LoC Rust) — see SURVEY.md §2.2.
"""

from dynamo_tpu.protocols.common import (
    BackendOutput,
    FinishReason,
    LLMEngineOutput,
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)

__all__ = [
    "BackendOutput",
    "FinishReason",
    "LLMEngineOutput",
    "PreprocessedRequest",
    "SamplingOptions",
    "StopConditions",
]
