"""Internal (post-preprocessing) request/response protocol types.

These are the types that cross the frontend->worker boundary: the preprocessor
turns an OpenAI request into a ``PreprocessedRequest`` (token ids + sampling +
stop conditions); the engine streams back ``LLMEngineOutput`` frames; the
backend (detokenizer) stage turns those into ``BackendOutput`` with text.

Parity: reference ``lib/llm/src/protocols/common/preprocessor.rs:25-58``
(``PreprocessedRequest``) and ``common/llm_backend.rs:27-83``
(``LLMEngineOutput``/``BackendOutput``).

All types are plain dataclasses with ``to_dict``/``from_dict`` so they can ride
msgpack frames without a serialization framework.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from enum import Enum
from typing import Any, Dict, List, Optional


class FinishReason(str, Enum):
    EOS = "eos"
    STOP = "stop"
    LENGTH = "length"
    CANCELLED = "cancelled"
    ERROR = "error"

    def to_openai(self) -> str:
        return {
            FinishReason.EOS: "stop",
            FinishReason.STOP: "stop",
            FinishReason.LENGTH: "length",
            FinishReason.CANCELLED: "stop",
            FinishReason.ERROR: "error",
        }[self]


def _asdict_shallow(obj) -> Dict[str, Any]:
    return {
        f.name: getattr(obj, f.name)
        for f in dataclasses.fields(obj)
        if getattr(obj, f.name) is not None
    }


@dataclass
class StopConditions:
    """When to stop generating.

    Parity: reference ``protocols/common/mod.rs`` ``StopConditions``.
    """

    max_tokens: Optional[int] = None
    stop: Optional[List[str]] = None  # stop strings (detokenizer-level)
    stop_token_ids: Optional[List[int]] = None
    min_tokens: Optional[int] = None
    ignore_eos: bool = False

    def to_dict(self) -> Dict[str, Any]:
        return _asdict_shallow(self)

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "StopConditions":
        return cls(**{k: d.get(k) for k in ("max_tokens", "stop", "stop_token_ids", "min_tokens")},
                   ignore_eos=bool(d.get("ignore_eos", False)))


@dataclass
class SamplingOptions:
    """Sampling parameters forwarded to the engine.

    Parity: reference ``protocols/common/mod.rs`` ``SamplingOptions``.
    """

    temperature: Optional[float] = None
    top_p: Optional[float] = None
    top_k: Optional[int] = None
    frequency_penalty: Optional[float] = None
    presence_penalty: Optional[float] = None
    repetition_penalty: Optional[float] = None
    seed: Optional[int] = None
    n: int = 1
    logprobs: Optional[int] = None
    # OpenAI logit_bias: token id -> additive bias (-100 bans, +100 forces)
    logit_bias: Optional[Dict[int, float]] = None
    # vLLM-style min_p: drop candidates whose probability is below
    # min_p * max-candidate-probability (0 = off)
    min_p: Optional[float] = None
    # guided decoding (OpenAI response_format -> engine/guided.py):
    # {"mode": "json"} or {"mode": "json_schema", "schema": {...}}
    guided: Optional[Dict[str, Any]] = None

    def to_dict(self) -> Dict[str, Any]:
        d = _asdict_shallow(self)
        d["n"] = self.n
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "SamplingOptions":
        kw = {k: d.get(k) for k in (
            "temperature", "top_p", "top_k", "frequency_penalty",
            "presence_penalty", "repetition_penalty", "seed", "logprobs",
            "min_p", "guided")}
        lb = d.get("logit_bias")
        if lb:
            # wire form may carry string token-id keys (OpenAI JSON)
            kw["logit_bias"] = {int(k): float(v) for k, v in lb.items()}
        return cls(n=int(d.get("n", 1)), **kw)


@dataclass
class PreprocessedRequest:
    """Tokenized request as sent from the frontend to a worker.

    Parity: reference ``protocols/common/preprocessor.rs:25-58``.

    ``estimated_prefix_hit_num_blocks`` is set by the KV router so the worker's
    scheduler can account for the expected prefix-cache hit.
    ``kv_transfer_params`` carries disaggregated prefill/decode handoff metadata
    (reference: vLLM ``kv_transfer_params`` flow, ``handlers.py:121-156``).
    """

    token_ids: List[int] = field(default_factory=list)
    request_id: str = ""
    model: str = ""
    stop_conditions: StopConditions = field(default_factory=StopConditions)
    sampling_options: SamplingOptions = field(default_factory=SamplingOptions)
    eos_token_ids: List[int] = field(default_factory=list)
    mdc_sum: Optional[str] = None  # model-card checksum for config-drift detection
    annotations: List[str] = field(default_factory=list)
    estimated_prefix_hit_num_blocks: Optional[int] = None
    kv_transfer_params: Optional[Dict[str, Any]] = None
    prefill_only: bool = False
    # >0 on a migration replay: the frontend's MigrationOperator stamps the
    # attempt number when it re-issues a dropped stream, so the receiving
    # worker can count replays it absorbs
    migration_attempt: int = 0
    # >0 on a migration replay/resume: how many TRAILING tokens of
    # ``token_ids`` were GENERATED by earlier legs of this stream (the
    # rebuild appends them to the prompt). The engine uses it to
    # reconstruct penalty windows — frequency/presence penalties count
    # generated tokens, which would otherwise read as prompt after a hop
    resumed_tokens: int = 0
    # end-to-end request deadline, absolute unix seconds (None = none).
    # Set by the HTTP frontend (config default or per-request override) and
    # propagated to the worker in the RPC ``req`` frame headers; expired
    # work is dropped instead of generating tokens nobody is waiting for.
    deadline_unix: Optional[float] = None
    # local-only (not serialized): annotation responses filled by the
    # preprocessor/router, emitted as SSE events by the HTTP layer
    annotations_payload: Dict[str, Any] = field(default_factory=dict)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "token_ids": list(self.token_ids),
            "request_id": self.request_id,
            "model": self.model,
            "stop_conditions": self.stop_conditions.to_dict(),
            "sampling_options": self.sampling_options.to_dict(),
            "eos_token_ids": list(self.eos_token_ids),
            "mdc_sum": self.mdc_sum,
            "annotations": list(self.annotations),
            "estimated_prefix_hit_num_blocks": self.estimated_prefix_hit_num_blocks,
            "kv_transfer_params": self.kv_transfer_params,
            "prefill_only": self.prefill_only,
            "migration_attempt": self.migration_attempt,
            "resumed_tokens": self.resumed_tokens,
            "deadline_unix": self.deadline_unix,
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "PreprocessedRequest":
        return cls(
            token_ids=list(d.get("token_ids", [])),
            request_id=d.get("request_id", ""),
            model=d.get("model", ""),
            stop_conditions=StopConditions.from_dict(d.get("stop_conditions") or {}),
            sampling_options=SamplingOptions.from_dict(d.get("sampling_options") or {}),
            eos_token_ids=list(d.get("eos_token_ids", [])),
            mdc_sum=d.get("mdc_sum"),
            annotations=list(d.get("annotations", [])),
            estimated_prefix_hit_num_blocks=d.get("estimated_prefix_hit_num_blocks"),
            kv_transfer_params=d.get("kv_transfer_params"),
            prefill_only=bool(d.get("prefill_only", False)),
            migration_attempt=int(d.get("migration_attempt", 0)),
            resumed_tokens=int(d.get("resumed_tokens", 0)),
            deadline_unix=d.get("deadline_unix"),
        )


@dataclass
class LLMEngineOutput:
    """One streamed frame from the engine: newly generated token ids.

    Parity: reference ``protocols/common/llm_backend.rs:27-55``.
    """

    token_ids: List[int] = field(default_factory=list)
    cum_log_probs: Optional[float] = None
    log_probs: Optional[List[float]] = None
    top_logprobs: Optional[List[Dict[int, float]]] = None
    finish_reason: Optional[FinishReason] = None
    error: Optional[str] = None
    kv_transfer_params: Optional[Dict[str, Any]] = None
    # completed-request accounting (filled on the final frame)
    prompt_tokens: Optional[int] = None
    completion_tokens: Optional[int] = None
    cached_tokens: Optional[int] = None
    # stage timing stamps (unix seconds), attached by the engine loop to the
    # FIRST emitted frame: enqueued_unix/admitted_unix/first_unix — the raw
    # material for the queue/prefill trace spans (utils/tracing.StageStitcher)
    timings: Optional[Dict[str, float]] = None

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {"token_ids": list(self.token_ids)}
        if self.finish_reason is not None:
            d["finish_reason"] = self.finish_reason.value
        for k in ("cum_log_probs", "log_probs", "top_logprobs", "error",
                  "kv_transfer_params", "prompt_tokens", "completion_tokens",
                  "cached_tokens", "timings"):
            v = getattr(self, k)
            if v is not None:
                d[k] = v
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "LLMEngineOutput":
        fr = d.get("finish_reason")
        return cls(
            token_ids=list(d.get("token_ids", [])),
            cum_log_probs=d.get("cum_log_probs"),
            log_probs=d.get("log_probs"),
            top_logprobs=d.get("top_logprobs"),
            finish_reason=FinishReason(fr) if fr else None,
            error=d.get("error"),
            kv_transfer_params=d.get("kv_transfer_params"),
            prompt_tokens=d.get("prompt_tokens"),
            completion_tokens=d.get("completion_tokens"),
            cached_tokens=d.get("cached_tokens"),
            timings=d.get("timings"),
        )


@dataclass
class BackendOutput:
    """Detokenized frame produced by the backend stage for the frontend.

    Parity: reference ``protocols/common/llm_backend.rs:60-83``.
    """

    token_ids: List[int] = field(default_factory=list)
    text: Optional[str] = None
    finish_reason: Optional[FinishReason] = None
    error: Optional[str] = None
    cum_log_probs: Optional[float] = None
    log_probs: Optional[List[float]] = None
    # OpenAI chat ``logprobs.content[]``-shaped dicts, one per emitted token
    # (token text, logprob, bytes, top_logprobs) — rendered by the backend,
    # which owns the tokenizer; None when the request didn't ask
    logprobs_content: Optional[List[Dict[str, Any]]] = None
    prompt_tokens: Optional[int] = None
    completion_tokens: Optional[int] = None
    cached_tokens: Optional[int] = None

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {"token_ids": list(self.token_ids)}
        if self.finish_reason is not None:
            d["finish_reason"] = self.finish_reason.value
        for k in ("text", "error", "cum_log_probs", "log_probs",
                  "logprobs_content", "prompt_tokens", "completion_tokens",
                  "cached_tokens"):
            v = getattr(self, k)
            if v is not None:
                d[k] = v
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "BackendOutput":
        fr = d.get("finish_reason")
        return cls(
            token_ids=list(d.get("token_ids", [])),
            text=d.get("text"),
            finish_reason=FinishReason(fr) if fr else None,
            error=d.get("error"),
            cum_log_probs=d.get("cum_log_probs"),
            log_probs=d.get("log_probs"),
            logprobs_content=d.get("logprobs_content"),
            prompt_tokens=d.get("prompt_tokens"),
            completion_tokens=d.get("completion_tokens"),
            cached_tokens=d.get("cached_tokens"),
        )


__all__ = [
    "FinishReason",
    "StopConditions",
    "SamplingOptions",
    "PreprocessedRequest",
    "LLMEngineOutput",
    "BackendOutput",
]
